"""Central MXNET_* environment-flag registry.

Reference parity: ``docs/faq/env_var.md`` — the reference scatters
``dmlc::GetEnv`` calls through the C++ tree; here every recognized knob
is declared once with its parser, default, and TPU-native disposition
(honored / delegated to XLA / not applicable), and ``describe()`` prints
the table.  Unknown ``MXNET_*`` variables in the environment trigger a
one-time warning instead of being silently ignored.
"""
from __future__ import annotations

import os
import warnings

__all__ = ["get", "describe", "FLAGS"]


def _pint(v):
    return int(v)


def _pbool(v):
    return str(v).lower() in ("1", "true", "yes", "on")


def _pfloat(v):
    return float(v)


# name -> (default, parser, disposition, note)
FLAGS = {
    "MXNET_ENGINE_TYPE": (
        "ThreadedEnginePerDevice", str, "honored",
        "NaiveEngine forces synchronous dispatch (race-detection oracle); "
        "anything else keeps jax async dispatch (engine.py)"),
    "MXNET_PLATFORM": (
        "", str, "honored",
        "pin the jax backend ('cpu'/'tpu') before init — multi-process "
        "launcher workers use this to stay off the single accelerator "
        "(__init__.py)"),
    "MXNET_PROFILER_AUTOSTART": (
        "0", _pbool, "honored", "start the jax trace at import"),
    "MXNET_TEST_PLATFORM": (
        "cpu", str, "honored",
        "test-suite backend selector: 'tpu' runs the op/gluon suites on "
        "the real chip with the cpu<->tpu consistency sweep "
        "(tests/conftest.py)"),
    "MXNET_PROFILER_MODE": (
        "0", _pint, "declared", "recognized; facade config is set via "
        "profiler.set_config"),
    "MXNET_CPU_WORKER_NTHREADS": (
        "4", _pint, "honored",
        "default preprocess_threads for ImageRecordIter"),
    "MXNET_SAFE_ACCUMULATION": (
        "0", _pbool, "honored",
        "accumulate fp16 sum/mean/norm in fp32 (ops/tensor.py)"),
    "MXNET_EXEC_BULK_EXEC_INFERENCE": (
        "1", _pbool, "delegated",
        "operator bulking — XLA fusion always bulks whole programs"),
    "MXNET_EXEC_BULK_EXEC_TRAIN": (
        "1", _pbool, "delegated", "see MXNET_EXEC_BULK_EXEC_INFERENCE"),
    "MXNET_EXEC_ENABLE_ADDTO": (
        "0", _pbool, "delegated",
        "gradient add-to elision — XLA does buffer donation/aliasing"),
    "MXNET_GPU_MEM_POOL_RESERVE": (
        "5", _pint, "delegated",
        "memory pooling is the XLA allocator's job on TPU"),
    "MXNET_GPU_WORKER_NTHREADS": (
        "2", _pint, "n/a", "no CUDA worker threads on TPU"),
    "MXNET_CUDNN_AUTOTUNE_DEFAULT": (
        "1", _pint, "n/a", "no cuDNN on TPU; XLA autotunes convolutions"),
    "MXNET_KVSTORE_REDUCTION_NTHREADS": (
        "4", _pint, "delegated",
        "reduction happens in one jitted program / ICI collective"),
    "MXNET_KVSTORE_BIGARRAY_BOUND": (
        "1000000", _pint, "declared",
        "recognized; the TCP PS does not shard big arrays"),
    "MXNET_ENABLE_GPU_P2P": ("1", _pbool, "n/a", "ICI replaces P2P"),
    "MXNET_UPDATE_ON_KVSTORE": (
        "1", _pbool, "honored", "Module/Trainer update placement"),
    "MXNET_MESH": (
        "", str, "honored",
        "default device-mesh spec for ShardedTrainer/bench front-ends: "
        "'axis=size' pairs over dp/fsdp/pp/ep/sp/mp/tp, e.g. "
        "'dp=2,fsdp=2,tp=2', or 'auto' (all local devices on dp); "
        "'' = no mesh (single-device semantics).  Resolved by "
        "parallel.mesh.resolve_mesh; explicit mesh= arguments win"),
    "MXNET_LAYOUT": (
        "", str, "honored",
        "default parameter-sharding layout name for ShardedTrainer: a "
        "registered spec-rule layout (data_parallel/fsdp/fsdp_tp or "
        "parallel.layout.register_layout additions); '' = pick the "
        "canonical layout for the mesh's axes (fsdp_tp when tp is "
        "present, fsdp for an fsdp axis, else data_parallel)"),
    "MXNET_DTYPE_POLICY": (
        "", str, "honored",
        "default mixed-precision dtype policy for every compile "
        "front-end (Executor/CachedOp/Module/ShardedTrainer/Predictor): "
        "'' or 'f32' = historical f32, 'bf16_mixed' = bf16 compute / "
        "f32 master params + loss scaling + per-layer f32 overrides, "
        "'bf16_pure', or a dtype_policy.register_policy addition.  "
        "Per-site override via dtype_policy="),
    "MXNET_LOSS_SCALE": (
        "65536", _pfloat, "honored",
        "initial dynamic loss scale for loss-scaling dtype policies "
        "(bf16_mixed): the loss is multiplied by the scale before the "
        "backward pass and gradients unscaled after, keeping small "
        "gradients out of the bf16 flush-to-zero band"),
    "MXNET_LOSS_SCALE_GROWTH_INTERVAL": (
        "2000", _pint, "honored",
        "consecutive finite steps before the dynamic loss scale doubles "
        "(capped at MXNET_LOSS_SCALE_MAX)"),
    "MXNET_LOSS_SCALE_BACKOFF": (
        "0.5", _pfloat, "honored",
        "multiplier applied to the loss scale when a scaled step "
        "overflows (the overflowed update is skipped in-graph and "
        "counted, never applied)"),
    "MXNET_LOSS_SCALE_MAX": (
        "16777216", _pfloat, "honored",
        "upper bound for dynamic loss-scale ramp-up (2^24 default)"),
    "MXNET_QUANTIZE_TOPK": (
        "5", _pint, "honored",
        "k for the int8 accuracy gate: tools/quantize_model.py compares "
        "fp32-of-record vs int8 top-k agreement on the recorded "
        "calibration batch before emitting an artifact"),
    "MXNET_QUANTIZE_MAX_DELTA": (
        "0.02", _pfloat, "honored",
        "maximum tolerated top-k accuracy delta (1 - agreement) for the "
        "int8 quantization gate; a larger measured delta refuses the "
        "artifact (tools/quantize_model.py exit code 3)"),
    "MXNET_REMAT_POLICY": (
        "", str, "honored",
        "default activation-remat policy for Executor/CachedOp/"
        "ShardedTrainer ('' = off; see mxnet_tpu.remat.list_policies())"),
    "MXNET_FUSION": (
        "", str, "honored",
        "default graph-fusion policy for Executor/CachedOp/Module/"
        "ShardedTrainer: '' = identical-math patterns + cost-table "
        "upgrades, 'off', 'all', or a pattern-name list "
        "(mxnet_tpu.symbol.fusion.list_patterns())"),
    "MXNET_FUSION_TUNE": (
        "", str, "honored",
        "path to the measured shape-keyed fusion cost table written by "
        "tools/autotune.py ('' = no table: only default-on patterns "
        "fire); override programmatically via config.fusion_cost_table"),
    "MXNET_COMPILE_CACHE": (
        "1", _pbool, "honored",
        "persistent XLA compilation cache: the second process-level run "
        "of the same program skips compilation (bench.py pays ~97 s "
        "cold)"),
    "MXNET_COMPILE_CACHE_DIR": (
        os.path.join(os.path.expanduser("~"), ".cache", "mxnet_tpu",
                     "xla"),
        str, "honored",
        "directory backing the persistent compilation cache"),
    "MXNET_AOT": (
        "0", _pbool, "honored",
        "ahead-of-time executable store (aot.py): jit'd hot paths "
        "(Executor, CachedOp, ShardedTrainer.step, serving.Predictor) "
        "lower+compile once and serialize the executable; later "
        "processes deserialize instead of recompiling — kills the "
        "~97 s bench.py cold start.  Per-site override via aot="),
    "MXNET_AOT_DIR": (
        os.path.join(os.path.expanduser("~"), ".cache", "mxnet_tpu",
                     "aot"),
        str, "honored",
        "directory backing the AOT executable store (content-hash "
        "keyed, digest-verified, version-gated; tools/prewarm.py "
        "pre-populates and --check validates it)"),
    "MXNET_AOT_MANIFEST": (
        "1", _pbool, "honored",
        "record every AOT-compiled executable's signature in the "
        "store's manifest.jsonl so tools/prewarm.py --manifest can "
        "rebuild and compile the whole workload ahead of rollout"),
    "MXNET_TRACE": (
        "0", _pbool, "honored",
        "hierarchical span tracing (tracing.py): step/request/checkpoint "
        "spans with trace/span/parent IDs into a bounded ring buffer, "
        "exportable as one Chrome/Perfetto trace.json; off = one branch "
        "per call site"),
    "MXNET_TRACE_BUFFER": (
        "4096", _pint, "honored",
        "span ring-buffer capacity (oldest spans evicted first; "
        "evictions counted in mxnet_tpu_trace_spans_dropped_total)"),
    "MXNET_FLIGHT_RECORDER": (
        "0", _pbool, "honored",
        "black-box postmortem bundles (trace + telemetry + thread stacks "
        "+ env/backend info) on non-finite guard trips, checkpoint "
        "digest failures, SIGTERM/SIGINT preemption, and unhandled "
        "step/fit/predict exceptions (tracing.record_crash)"),
    "MXNET_FLIGHT_RECORDER_DIR": (
        "", str, "honored",
        "flight-recorder bundle directory ('' = ./flight_recorder)"),
    "MXNET_TELEMETRY": (
        "0", _pbool, "honored",
        "runtime metrics registry (telemetry.py): step/serving/"
        "checkpoint/compile series, Prometheus scrape() + JSON dump(); "
        "off = one flag-check per call site"),
    "MXNET_TELEMETRY_INTERVAL": (
        "30", _pfloat, "honored",
        "TelemetryReporter default snapshot interval in seconds"),
    "MXNET_TELEMETRY_PORT": (
        "0", _pint, "honored",
        "Prometheus HTTP scrape endpoint: serve telemetry.scrape() at "
        "http://0.0.0.0:PORT/metrics with a /healthz readiness probe "
        "for the process lifetime (telemetry.serve_scrape; 0 = off).  "
        "Pair with MXNET_TELEMETRY=1 for non-zero series"),
    "MXNET_EVENTS": (
        "0", _pbool, "honored",
        "wide-event request observability (events.py): one structured "
        "JSONL record per unit of work (serving request, TokenServer "
        "generation, train-step window, checkpoint save/load, AOT "
        "compile/load) with typed outcome, stage latency split, trace "
        "id, and perf_ledger provenance; off = one branch per call "
        "site.  Sheds/deadline/error outcomes are always kept"),
    "MXNET_EVENTS_PATH": (
        "", str, "honored",
        "JSONL file the bounded background event writer appends kept "
        "wide events to (O_APPEND; a full queue drops + counts, never "
        "blocks serving).  '' = in-memory ring only (/requestz and "
        "flight-recorder bundles still see the last 512 events)"),
    "MXNET_EVENTS_SAMPLE": (
        "1.0", _pfloat, "honored",
        "keep probability for ok-outcome wide events below the tail "
        "threshold (head sampling).  Errors, sheds, deadline-exceeded, "
        "evictions and the slowest percentile per kind are ALWAYS "
        "kept regardless of this knob"),
    "MXNET_PERF_LEDGER": (
        "", str, "honored",
        "append-only JSONL run ledger every bench emitter "
        "(bench.py, tools/bench_*.py) writes its schema-versioned "
        "BENCH records into via perf_ledger.emit — the queryable perf "
        "history tools/perf_report.py and tools/perf_gate.py consume "
        "('' = records print but nothing persists)"),
    "MXNET_PEAK_TFLOPS": (
        "", str, "honored",
        "accelerator peak TFLOP/s for the MFU gauge (overrides the "
        "docs/mfu_probe.json ceiling; '' = probe artifact or no MFU)"),
    "MXNET_ASYNC_METRICS": (
        "0", _pbool, "honored",
        "non-blocking train-step metrics (parallel/train.py): step() "
        "never syncs on the loss; device-resident accumulators are "
        "pulled by a bounded background fetch and TRAIN_LOSS/heartbeat "
        "consume the last completed fetch.  Hard syncs remain only at "
        "checkpoint/drain boundaries.  Per-trainer override via "
        "async_metrics="),
    "MXNET_STEPS_PER_CALL": (
        "1", _pint, "honored",
        "K-step fused train loop: ShardedTrainer.step_many runs K "
        "pre-staged microbatches as ONE XLA call (lax.scan over a "
        "donated carry), amortizing per-step dispatch.  1 = one program "
        "per step (the historical path).  Per-trainer override via "
        "steps_per_call="),
    "MXNET_DEVICE_PREFETCH": (
        "2", _pint, "honored",
        "default depth of io.DevicePrefetcher: batches whose host->HBM "
        "upload (sharded over the layout's data axes) is staged ahead "
        "of the consuming train step; 0 disables the wrapper "
        "(DataLoader device_prefetch= / io/prefetch.py)"),
    "MXNET_NONFINITE_POLICY": (
        "warn", str, "honored",
        "default step-guard policy for NaN/Inf losses & gradient norms: "
        "off|warn|skip|raise — 'skip' discards the update and keeps the "
        "previous params/optimizer state (checkpoint.nonfinite_policy)"),
    "MXNET_CHECKPOINT_KEEP": (
        "3", _pint, "honored",
        "CheckpointManager keep-last-N retention default"),
    "MXNET_CHECKPOINT_ASYNC": (
        "1", _pbool, "honored",
        "CheckpointManager default save mode: snapshot to host, then "
        "serialize/fsync in a background thread (wait() is the barrier)"),
    "MXNET_CKPT_SHARDED": (
        "0", _pbool, "honored",
        "CheckpointManager default for sharded=: every process writes "
        "only its addressable shards (shard-<host>.npz + digest "
        "sidecar), process 0 commits the global manifest last after "
        "the cross-host durability barrier (pod-scale elastic "
        "checkpoints; see docs/fault_tolerance.md)"),
    "MXNET_DIST_COORDINATOR": (
        "", str, "honored",
        "jax.distributed coordinator address host:port for "
        "parallel.bootstrap_distributed (wins over the legacy "
        "DMLC_PS_ROOT_URI/MXTPU_COORDINATOR spellings); '' means not "
        "configured -> single-process"),
    "MXNET_DIST_NUM_PROCS": (
        "0", _pint, "honored",
        "process count for the jax.distributed bootstrap (<=1 means "
        "single-process; falls back to DMLC_NUM_WORKER/MXTPU_NUM_PROCS)"),
    "MXNET_DIST_PROC_ID": (
        "-1", _pint, "honored",
        "this process's id for the jax.distributed bootstrap (-1 = "
        "unset -> falls back to DMLC_RANK/MXTPU_PROC_ID, then 0)"),
    "MXNET_DIST_CONNECT_RETRIES": (
        "3", _pint, "honored",
        "bootstrap_distributed re-attempts after the first coordinator "
        "connect failure (exponential backoff between attempts)"),
    "MXNET_DIST_CONNECT_BACKOFF": (
        "0.5", _pfloat, "honored",
        "initial backoff seconds between coordinator connect retries "
        "(doubles per attempt, jittered)"),
    "MXNET_DIST_BARRIER_TIMEOUT": (
        "120", _pfloat, "honored",
        "sharded-save durability barrier: seconds process 0 (and every "
        "peer) waits for all shard digest sidecars before the manifest "
        "commit / before giving up on a dead peer"),
    "MXNET_DIST_PREEMPT_GATE": (
        "1", _pint, "honored",
        "coordinated preemption commit: step-boundaries of headroom "
        "between the signalled host's committed step and the pod-wide "
        "final-checkpoint step (bounds host dispatch drift; raise for "
        "deep async pipelines)"),
    "MXNET_FLEET_SPOOL": (
        "", str, "honored",
        "fleet-observatory spool directory (fleet.py): each rank "
        "publishes atomic metric/breakdown/trace snapshots here and "
        "the collector (tools/fleetz.py, /fleetz) merges them into a "
        "pod view with straggler attribution; '' = observatory off"),
    "MXNET_FLEET_INTERVAL": (
        "5", _pfloat, "honored",
        "seconds between background fleet snapshot publishes "
        "(FleetPublisher.start); each publish is one registry collect "
        "+ two atomic file writes into the spool"),
    "MXNET_FLEET_STALE": (
        "30", _pfloat, "honored",
        "fleet collector staleness cut in seconds: a rank whose last "
        "snapshot is older (clock-offset corrected) is marked stale "
        "and excluded from straggler scoring — a dead rank degrades "
        "to a stale row, it never blocks the merge"),
    "MXNET_FLEET_CLOCK_OFFSET": (
        "0", _pfloat, "honored",
        "wall-clock offset in seconds added to every timestamp this "
        "rank's FleetPublisher records — deterministic skew injection "
        "for clock-offset-estimation drills (tests); keep 0 in "
        "production"),
    "MXNET_GLUON_REPO": (
        "", str, "honored",
        "base URL for gluon model_zoo weight downloads (file:// works "
        "for air-gapped mirrors); '' disables downloads "
        "(model_store.get_model_file)"),
    "MXNET_GOODPUT_DIR": (
        "", str, "honored",
        "goodput-ledger job directory (goodput.py): each process "
        "incarnation appends typed wall-clock segments (productive "
        "step, compile, checkpoint save/restore, data wait, startup, "
        "drain) to its own crash-safe JSONL here and the reader "
        "(tools/goodputz.py, /goodputz, perf_report --goodput) merges "
        "every incarnation of every rank into one job-lifetime "
        "goodput/badput report with preemption lost-work pricing; "
        "'' = ledger off"),
    "MXNET_GOODPUT_FLUSH_EVERY": (
        "16", _pint, "honored",
        "goodput-ledger sidecar cadence: records appended between "
        "prefix-digest sidecar commits (GoodputRecorder.flush); the "
        "tail past the last flush is still read best-effort under the "
        "torn-line discipline, so this bounds re-hash work, not data "
        "loss"),
    "MXNET_HOME": (
        os.path.join("~", ".mxnet"), str, "honored",
        "data/cache root for gluon contrib dataset downloads "
        "(gluon/contrib/data.py)"),
    "MXNET_SERVING_QUEUE": (
        "64", _pint, "honored",
        "AsyncPredictor bounded request-queue depth (serving_async.py); "
        "a full queue rejects non-blocking submits with a typed "
        "Overloaded error instead of growing latency without bound"),
    "MXNET_SERVING_DEADLINE_MS": (
        "0", _pfloat, "honored",
        "AsyncPredictor default per-request deadline in milliseconds "
        "(0 = none): expired requests fail with DeadlineExceeded — in "
        "the queue via the sweep, at dispatch pickup, or on late "
        "completion — instead of silently blowing the client timeout"),
    "MXNET_SERVING_MAX_INFLIGHT": (
        "0", _pint, "honored",
        "AsyncPredictor cap on admitted-but-uncompleted requests, "
        "queued + claimed (0 = auto: queue depth + 2 x chain x B x "
        "replicas — pipeline capacity in requests, so it binds when "
        "dispatches are stuck, not before the queue); past it submits "
        "shed with "
        "Overloaded(reason='inflight') or block when backpressure is "
        "requested"),
    "MXNET_SERVING_WARM_POOL": (
        "0", _pint, "honored",
        "AsyncPredictor default warm-pool size: N spare Predictor "
        "replicas pre-built (through the AOT store when enabled) so a "
        "replica ejection swaps a canary-verified spare in "
        "automatically instead of waiting for operator heal()"),
    "MXNET_SERVING_HEAL_PROBE": (
        "0", _pfloat, "honored",
        "seconds between auto-heal canary probes of ejected "
        "AsyncPredictor replicas (0 = no probing): a probe dispatches "
        "one known-good batch and re-admits the replica on success"),
    "MXNET_GATEWAY_PORT": (
        "0", _pint, "honored",
        "HTTP serving gateway listen port (gateway.py; 0 = ephemeral, "
        "the bound port is on Gateway.port).  The gateway also serves "
        "the scrape routes (/metrics /healthz /statusz /varz "
        "/requestz) on the same listener"),
    "MXNET_GATEWAY_MAX_BODY": (
        "1048576", _pint, "honored",
        "gateway request-body byte cap: a Content-Length above it is "
        "refused 413 before reading a byte, so oversized bodies can "
        "never hold a handler thread or its memory"),
    "MXNET_GATEWAY_READ_TIMEOUT_S": (
        "5", _pfloat, "honored",
        "gateway socket read timeout while receiving a request body: "
        "a slow-loris client trickling bytes slower than this is cut "
        "with 408 instead of pinning a handler thread"),
    "MXNET_GATEWAY_QUOTA_QPS": (
        "0", _pfloat, "honored",
        "per-tenant token-bucket refill rate in requests/second "
        "(0 = quotas off): a tenant over its bucket gets 429 with "
        "Retry-After sized to the refill wait"),
    "MXNET_GATEWAY_QUOTA_BURST": (
        "8", _pint, "honored",
        "per-tenant token-bucket capacity: how many requests a tenant "
        "may burst above its steady MXNET_GATEWAY_QUOTA_QPS rate"),
    "MXNET_GATEWAY_QUEUE": (
        "16", _pint, "honored",
        "gateway per-tenant fair-queue depth: a tenant with this many "
        "requests already waiting for a dispatch permit sheds the "
        "next one typed (Overloaded('queue') -> 429)"),
    "MXNET_GATEWAY_CONCURRENCY": (
        "8", _pint, "honored",
        "gateway dispatch permits shared across tenants: concurrent "
        "backend requests; freed permits go to the queued tenant with "
        "the smallest weighted-fair virtual finish time"),
    "MXNET_GATEWAY_DRAIN_S": (
        "10", _pfloat, "honored",
        "gateway close()/SIGTERM drain budget in seconds: /healthz "
        "flips 503 first, new requests shed 503, open streams get "
        "this long to finish before the listener stops"),
    "MXNET_GATEWAY_MAX_TENANTS": (
        "256", _pint, "honored",
        "cap on distinct X-Tenant values tracked by the gateway: "
        "tenants past the cap collapse onto one shared overflow "
        "key (bucket/queue/metric label), so minting unique tenant "
        "headers cannot grow per-tenant state without bound"),
    "MXNET_DECODE_SLOTS": (
        "8", _pint, "honored",
        "generate.GenerationEngine default decode batch slots: the "
        "fixed-shape continuous-batching width of the compiled decode "
        "step (one KV-cache lane per slot)"),
    "MXNET_DECODE_CACHE_LEN": (
        "256", _pint, "honored",
        "default KV-cache ring length per slot (positions kept per "
        "sequence; capped at the model's max_len).  Generation past "
        "the ring attends over a sliding window"),
    "MXNET_DECODE_BUCKETS": (
        "32,64,128,256", str, "honored",
        "comma list of prefill length buckets: a prompt pads up to "
        "the smallest bucket >= its length, so prefill compiles one "
        "executable per bucket (each a distinct AOT manifest row "
        "tools/prewarm.py can warm) instead of one per prompt length"),
    "MXNET_DECODE_QUEUE": (
        "64", _pint, "honored",
        "generate.TokenServer admission-queue depth: a full queue "
        "rejects with the typed Overloaded('queue') error"),
    "MXNET_DECODE_DEADLINE_MS": (
        "0", _pfloat, "honored",
        "default per-request decode deadline (0 = none): an expired "
        "request fails with DeadlineExceeded(stage='prefill'|'decode') "
        "and its cache slot is evicted (reason='deadline')"),
    "MXNET_DECODE_MAX_NEW": (
        "128", _pint, "honored",
        "default cap on generated tokens per request (finish_reason "
        "'length'); per-submit max_new_tokens= overrides"),
    "MXNET_DECODE_PAGED": (
        "0", _pint, "honored",
        "tools default engine selection (bench_decode/prewarm): 1 "
        "builds the paged engine (generate.PagedGenerationEngine: page "
        "pool + prefix sharing + chunked prefill) instead of the "
        "per-slot KV ring; library callers pick the class directly"),
    "MXNET_DECODE_PAGE_SIZE": (
        "16", _pint, "honored",
        "positions per KV page in the paged engine's pool; a slot "
        "holds ceil(cache_len/page_size) pages and prefix sharing is "
        "page-aligned (smaller pages share more, dispatch more "
        "scatter rows)"),
    "MXNET_DECODE_PAGES": (
        "0", _pint, "honored",
        "total pages in the paged engine's pool, incl. the reserved "
        "trash page (0 = auto: slots x pages_per_slot + 1, the floor "
        "at which admission-time allocation can never starve a "
        "mid-flight decode)"),
    "MXNET_DECODE_PREFILL_CHUNK": (
        "32", _pint, "honored",
        "chunked-prefill chunk length: prompts stream into the paged "
        "engine this many positions per dispatch, one chunk per "
        "TokenServer loop tick, so a long admission interleaves with "
        "decode steps instead of stalling active lanes' ITL"),
    "MXNET_DECODE_SPEC_K": (
        "0", _pint, "honored",
        "n-gram speculative decoding draft length for the paged "
        "engine (0 = off): each decode step carries up to K drafted "
        "tokens and verifies them in one fixed-shape dispatch; "
        "exact-match acceptance keeps output identical to "
        "non-speculative sampling"),
    "MXNET_DECODE_SPEC_NGRAM": (
        "2", _pint, "honored",
        "suffix length the n-gram speculator matches against the "
        "sequence's own history (prompt + generated) to source drafts"),
    "MXNET_DECODE_PREFIX_SHARE": (
        "1", _pint, "honored",
        "paged-engine prefix sharing: content-hash full prompt pages "
        "and attach later prompts with the same page-aligned prefix "
        "to the cached pages refcounted (copy-on-write by alignment; "
        "0 disables)"),
    "DMLC_ROLE": ("worker", str, "honored", "dist kvstore role"),
    "DMLC_PS_ROOT_URI": ("", str, "honored", "dist kvstore server host"),
    "DMLC_PS_ROOT_PORT": ("9091", _pint, "honored",
                          "dist kvstore server port"),
    "DMLC_WORKER_RANK": ("0", _pint, "honored", "dist worker rank"),
    "DMLC_RANK": ("0", _pint, "honored", "dist rank (fallback name)"),
    "DMLC_NUM_WORKER": ("1", _pint, "honored", "dist worker count"),
    "DMLC_NUM_SERVER": ("1", _pint, "honored", "dist server count"),
}

_warned = set()


def get(name):
    """Parsed value of a registered flag (env overrides default)."""
    default, parser, _disp, _note = FLAGS[name]
    raw = os.environ.get(name, default)
    try:
        return parser(raw)
    except (TypeError, ValueError):
        if name not in _warned:
            _warned.add(name)
            warnings.warn("invalid value %r for %s; using default %r"
                          % (raw, name, default))
        return parser(default)


def warn_unknown():
    """One-time warning for unrecognized MXNET_* environment variables."""
    for name in os.environ:
        if name.startswith("MXNET_") and name not in FLAGS and \
                name not in _warned:
            _warned.add(name)
            warnings.warn("environment variable %s is not recognized by "
                          "mxnet_tpu (see mxnet_tpu.config.FLAGS)" % name)


def describe():
    """Human-readable flag table (reference env_var.md equivalent)."""
    rows = ["%-36s %-9s default=%-10s %s" % (n, d[2], d[0], d[3])
            for n, d in sorted(FLAGS.items())]
    return "\n".join(rows)


def _cache_deser_affected(version):
    """Is ``version`` of jax affected by the multi-device CPU persistent-
    cache mis-deserialization (repro in docs/perf_notes.md: cache-warm
    8-virtual-device allreduce returns wrong loss)?  Observed on the
    0.4.x line; treat everything below 0.5.0 as affected and newer
    releases as fixed (the deserialization path was rewritten), so the
    cache comes back exactly where it matters most as soon as the
    installed jax moves off the buggy line.  Unparseable versions count
    as affected — the failure mode of a wrong "safe" is silently wrong
    training losses."""
    try:
        parts = tuple(int(x) for x in str(version).split(".")[:2])
    except (TypeError, ValueError):
        return True
    return parts < (0, 5)


def compile_cache_safe(jax_version=None):
    """Whether the persistent compile cache is safe to enable by default.

    jax 0.4.x deserializes MULTI-DEVICE CPU executables incorrectly
    (measured: a cache-warm 8-virtual-device allreduce step returns
    wrong loss values — examples/distributed_horovod_style.py fails its
    equivalence check on the second run).  The guard is VERSION-GATED:
    under a forced-host-device-count CPU mesh the bootstrap skips the
    cache only when the installed jax is on an affected line
    (:func:`_cache_deser_affected`); unaffected jax keeps the cache
    even there.  Real accelerators and plain single-device CPU always
    keep it, and an explicit ``enable_compile_cache()`` call still
    works everywhere.  ``jax_version`` overrides the installed version
    (tests)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" in flags:
        multi = False
        for tok in flags.split():
            if tok.startswith("--xla_force_host_platform_device_count"):
                try:
                    multi = int(tok.split("=", 1)[1]) > 1
                except (IndexError, ValueError):
                    multi = True
        if multi:
            if jax_version is None:
                import jax

                jax_version = jax.__version__
            return not _cache_deser_affected(jax_version)
    return True


def fusion_cost_table(table):
    """Install the process-wide fusion cost table (same switch as the
    ``MXNET_FUSION_TUNE`` env path, callable after import): a JSON
    path, a ``fusion_cost.CostTable``/dict, or None to force no table.
    ``tools/autotune.py`` writes compatible tables."""
    from . import fusion_cost

    fusion_cost.set_cost_table(table)


def enable_aot(store=True):
    """Install the process-wide AOT executable store (same switch as
    ``MXNET_AOT``/``MXNET_AOT_DIR``, callable after import): a store
    directory path, ``True`` (default dir), or ``False`` to force AOT
    off.  Per-site ``aot=`` arguments still override.

    Call BEFORE the first compile when this process should *persist*
    artifacts on CPU: enabling injects the codegen flag that keeps
    serialized CPU executables self-contained, which XLA only honors
    if its flags have not been parsed yet (``MXNET_AOT=1`` in the
    environment gets it unconditionally right — the package bootstrap
    sets the flag at import)."""
    from . import aot

    aot.set_store(store)


def enable_telemetry(on=True):
    """Toggle the runtime metrics registry (same switch as the
    ``MXNET_TELEMETRY`` env flag, callable after import)."""
    from . import telemetry

    if on:
        telemetry.enable()
    else:
        telemetry.disable()


def enable_events(on=True, path=None, sample=None):
    """Toggle wide-event emission (same switch as ``MXNET_EVENTS``;
    ``path``/``sample`` override ``MXNET_EVENTS_PATH`` /
    ``MXNET_EVENTS_SAMPLE``)."""
    from . import events

    if on:
        events.enable(path=path, sample=sample)
    else:
        events.disable()


def enable_tracing(on=True):
    """Toggle hierarchical span tracing (same switch as ``MXNET_TRACE``,
    callable after import)."""
    from . import tracing

    if on:
        tracing.enable()
    else:
        tracing.disable()


def enable_flight_recorder(on=True, directory=None):
    """Toggle the crash flight recorder (same switch as
    ``MXNET_FLIGHT_RECORDER``; ``directory`` overrides
    ``MXNET_FLIGHT_RECORDER_DIR``)."""
    from . import tracing

    if on:
        tracing.enable_flight_recorder(directory)
    else:
        tracing.disable_flight_recorder()


def enable_compile_cache(cache_dir=None, min_compile_time_secs=None):
    """Point jax's persistent compilation cache at ``cache_dir``.

    Called from package bootstrap when ``MXNET_COMPILE_CACHE`` is on
    (the default): a second process compiling the same XLA program loads
    the cached executable from disk instead of recompiling — bench.py's
    ~97 s ResNet-50 train-step compile becomes a one-time cost per
    machine.  Safe to call before or after backend init (the flag is
    read at compile time).  Returns the cache dir, or None when the
    cache could not be enabled (unwritable dir, jax too old).
    """
    import jax

    cache_dir = cache_dir or get("MXNET_COMPILE_CACHE_DIR")
    prev = jax.config.jax_compilation_cache_dir
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        if min_compile_time_secs is not None:
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              float(min_compile_time_secs))
    except Exception as e:
        # roll back so a False/None return really means "cache off" —
        # a half-applied config would cache executables while the
        # caller believes it does not
        try:
            jax.config.update("jax_compilation_cache_dir", prev)
        except Exception:
            pass
        warnings.warn("persistent compilation cache disabled: %s" % e)
        return None
    if prev != cache_dir:
        # jax pins the cache object to the dir seen at first use;
        # re-pointing after any compile needs an explicit reset.
        # Best-effort private API: at bootstrap nothing has compiled
        # yet, so a missing reset hook does not invalidate the enable.
        try:
            from jax._src import compilation_cache as _cc

            _cc.reset_cache()
        except Exception:
            pass
    return cache_dir


def markdown_table():
    """``docs/env_vars.md`` table body — regenerate that file with
    ``python -m mxnet_tpu.config`` whenever a flag is added (the
    tests/test_env_knobs.py guard fails until it is)."""
    rows = ["| `%s` | %s | `%s` | %s |"
            % (n, d[2], d[0] if d[0] != "" else "''",
               d[3].replace("|", "\\|"))
            for n, d in sorted(FLAGS.items())]
    return "\n".join(["| knob | disposition | default | notes |",
                      "| --- | --- | --- | --- |"] + rows)


if __name__ == "__main__":
    print(markdown_table())
