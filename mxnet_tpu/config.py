"""Central MXNET_* environment-flag registry.

Reference parity: ``docs/faq/env_var.md`` — the reference scatters
``dmlc::GetEnv`` calls through the C++ tree; here every recognized knob
is declared once with its parser, default, and TPU-native disposition
(honored / delegated to XLA / not applicable), and ``describe()`` prints
the table.  Unknown ``MXNET_*`` variables in the environment trigger a
one-time warning instead of being silently ignored.
"""
from __future__ import annotations

import os
import warnings

__all__ = ["get", "describe", "FLAGS"]


def _pint(v):
    return int(v)


def _pbool(v):
    return str(v).lower() in ("1", "true", "yes", "on")


# name -> (default, parser, disposition, note)
FLAGS = {
    "MXNET_ENGINE_TYPE": (
        "ThreadedEnginePerDevice", str, "honored",
        "NaiveEngine forces synchronous dispatch (race-detection oracle); "
        "anything else keeps jax async dispatch (engine.py)"),
    "MXNET_PLATFORM": (
        "", str, "honored",
        "pin the jax backend ('cpu'/'tpu') before init — multi-process "
        "launcher workers use this to stay off the single accelerator "
        "(__init__.py)"),
    "MXNET_PROFILER_AUTOSTART": (
        "0", _pbool, "honored", "start the jax trace at import"),
    "MXNET_TEST_PLATFORM": (
        "cpu", str, "honored",
        "test-suite backend selector: 'tpu' runs the op/gluon suites on "
        "the real chip with the cpu<->tpu consistency sweep "
        "(tests/conftest.py)"),
    "MXNET_PROFILER_MODE": (
        "0", _pint, "declared", "recognized; facade config is set via "
        "profiler.set_config"),
    "MXNET_CPU_WORKER_NTHREADS": (
        "4", _pint, "honored",
        "default preprocess_threads for ImageRecordIter"),
    "MXNET_SAFE_ACCUMULATION": (
        "0", _pbool, "honored",
        "accumulate fp16 sum/mean/norm in fp32 (ops/tensor.py)"),
    "MXNET_EXEC_BULK_EXEC_INFERENCE": (
        "1", _pbool, "delegated",
        "operator bulking — XLA fusion always bulks whole programs"),
    "MXNET_EXEC_BULK_EXEC_TRAIN": (
        "1", _pbool, "delegated", "see MXNET_EXEC_BULK_EXEC_INFERENCE"),
    "MXNET_EXEC_ENABLE_ADDTO": (
        "0", _pbool, "delegated",
        "gradient add-to elision — XLA does buffer donation/aliasing"),
    "MXNET_GPU_MEM_POOL_RESERVE": (
        "5", _pint, "delegated",
        "memory pooling is the XLA allocator's job on TPU"),
    "MXNET_GPU_WORKER_NTHREADS": (
        "2", _pint, "n/a", "no CUDA worker threads on TPU"),
    "MXNET_CUDNN_AUTOTUNE_DEFAULT": (
        "1", _pint, "n/a", "no cuDNN on TPU; XLA autotunes convolutions"),
    "MXNET_KVSTORE_REDUCTION_NTHREADS": (
        "4", _pint, "delegated",
        "reduction happens in one jitted program / ICI collective"),
    "MXNET_KVSTORE_BIGARRAY_BOUND": (
        "1000000", _pint, "declared",
        "recognized; the TCP PS does not shard big arrays"),
    "MXNET_ENABLE_GPU_P2P": ("1", _pbool, "n/a", "ICI replaces P2P"),
    "MXNET_UPDATE_ON_KVSTORE": (
        "1", _pbool, "honored", "Module/Trainer update placement"),
    "DMLC_ROLE": ("worker", str, "honored", "dist kvstore role"),
    "DMLC_PS_ROOT_URI": ("", str, "honored", "dist kvstore server host"),
    "DMLC_PS_ROOT_PORT": ("9091", _pint, "honored",
                          "dist kvstore server port"),
    "DMLC_WORKER_RANK": ("0", _pint, "honored", "dist worker rank"),
    "DMLC_RANK": ("0", _pint, "honored", "dist rank (fallback name)"),
    "DMLC_NUM_WORKER": ("1", _pint, "honored", "dist worker count"),
    "DMLC_NUM_SERVER": ("1", _pint, "honored", "dist server count"),
}

_warned = set()


def get(name):
    """Parsed value of a registered flag (env overrides default)."""
    default, parser, _disp, _note = FLAGS[name]
    raw = os.environ.get(name, default)
    try:
        return parser(raw)
    except (TypeError, ValueError):
        if name not in _warned:
            _warned.add(name)
            warnings.warn("invalid value %r for %s; using default %r"
                          % (raw, name, default))
        return parser(default)


def warn_unknown():
    """One-time warning for unrecognized MXNET_* environment variables."""
    for name in os.environ:
        if name.startswith("MXNET_") and name not in FLAGS and \
                name not in _warned:
            _warned.add(name)
            warnings.warn("environment variable %s is not recognized by "
                          "mxnet_tpu (see mxnet_tpu.config.FLAGS)" % name)


def describe():
    """Human-readable flag table (reference env_var.md equivalent)."""
    rows = ["%-36s %-9s default=%-10s %s" % (n, d[2], d[0], d[3])
            for n, d in sorted(FLAGS.items())]
    return "\n".join(rows)
