"""Worker side of the C train/NDArray ABI (cpp/mxtpu_api.cc).

Reference counterpart: the core of ``include/mxnet/c_api.h`` /
``src/c_api/c_api.cc`` — NDArray CRUD, imperative invoke by op name,
symbol load + infer-shape, executor bind/forward/backward: the subset
that powers a cpp-package-style client that *trains*, not just
predicts.  Same worker-process design as predict_worker.py (no
libpython linkage in the host app, crash isolation; the per-call IPC is
noise next to the XLA compute).

Wire protocol (little-endian, over stdin/stdout; shared framing with
the predict worker):
    request  = u8 opcode | u64 payload_len | payload
    response = u8 status (0 ok, 1 error) | u64 payload_len | payload

Handles are u64 ids into per-kind tables; 0 is never issued.  Tensor
payloads are raw host-order bytes (f32 or i32), like the predict ABI.

opcodes:
     0 CLOSE        worker exits
     1 ND_CREATE    u8 dtype(0=f32,1=i32) u8 fill(0=zeros,1=ones)
                    u32 ndim u32 dims[]                  -> u64 h
     2 ND_FROMDATA  u8 dtype u32 ndim u32 dims[] raw     -> u64 h
     3 ND_TOHOST    u64 h                                -> raw bytes
     4 ND_SHAPE     u64 h                           -> u32 ndim u32 dims[]
     5 ND_FREE      u64 h                                -> ()
     6 INVOKE       u32 oplen op u32 n_in u64 h[] u32 n_attr
                    (u32 klen k u32 vlen v)*       -> u32 n_out u64 h[]
     7 SYM_FROMJSON json bytes                           -> u64 h
     8 SYM_ARGS     u64 h                     -> u32 n (u32 len str)*
     9 SYM_INFER    u64 h u32 n (u32 nlen name u32 ndim u32 dims[])*
                    -> u32 n_args (u32 ndim u32 dims[])*  [in SYM_ARGS
                       order]  u32 n_out (u32 ndim u32 dims[])*
    10 EXEC_BIND    u64 sym u32 n_args (u32 nlen name u64 h)*
                    u32 n_aux (u32 nlen name u64 h)* u8 with_grad
                    -> u64 h   (with_grad=1 allocates zero grad arrays
                       for every bound arg)
    11 EXEC_FWD     u64 h u8 is_train          -> u32 n_out u64 h[]
                    (fresh ndarray handles per call)
    12 EXEC_BWD     u64 h u32 n_heads u64 h[]  -> ()  (0 heads = loss
                    op semantics: ones_like head grads)
    13 EXEC_GRAD    u64 h u32 nlen name        -> u64 h (stable across
                    backward calls; the executor rebinds in place)
    14 SEED         u64 seed                   -> ()
    15 SYM_FREE     u64 h                      -> ()
    16 EXEC_FREE    u64 h                      -> ()
    17 ND_COPYFROM  u64 h raw                  -> ()  (SyncCopyFromCPU:
                    rebind the array's data in place, shape/dtype kept)
"""
from __future__ import annotations

import os
import struct
import sys


def _read_exact(f, n):
    buf = b""
    while len(buf) < n:
        chunk = f.read(n - len(buf))
        if not chunk:
            raise EOFError("client closed the pipe")
        buf += chunk
    return buf


class _Reader:
    def __init__(self, payload):
        self.p = payload
        self.off = 0

    def u8(self):
        (v,) = struct.unpack_from("<B", self.p, self.off)
        self.off += 1
        return v

    def u32(self):
        (v,) = struct.unpack_from("<I", self.p, self.off)
        self.off += 4
        return v

    def u64(self):
        (v,) = struct.unpack_from("<Q", self.p, self.off)
        self.off += 8
        return v

    def dims(self):
        nd = self.u32()
        out = struct.unpack_from("<%dI" % nd, self.p, self.off)
        self.off += 4 * nd
        return tuple(int(d) for d in out)

    def string(self):
        n = self.u32()
        s = self.p[self.off:self.off + n].decode("utf-8")
        self.off += n
        return s

    def rest(self):
        return self.p[self.off:]


def _shape_reply(shape):
    return struct.pack("<I", len(shape)) + \
        struct.pack("<%dI" % len(shape), *[int(d) for d in shape])


_DTYPES = ("float32", "int32")


class _Server:
    def __init__(self):
        self.nd = {}
        self.sym = {}
        self.exe = {}
        self._next = 1
        self._nd_rev = {}   # id(ndarray) -> handle (O(1) reuse lookup)

    def _new(self, table, obj):
        if table is self.nd:
            # reuse the existing handle for an object already in the
            # table (in-place-mutating ops return their input; without
            # reuse every sgd_update would leak a table entry).  ids are
            # stable here because the table holds a strong reference.
            h = self._nd_rev.get(id(obj))
            if h is not None:
                return h
        h = self._next
        self._next += 1
        table[h] = obj
        if table is self.nd:
            self._nd_rev[id(obj)] = h
        return h

    # -- ndarray -----------------------------------------------------------

    def nd_create(self, r):
        import numpy as np

        from .ndarray.ndarray import array

        dtype = _DTYPES[r.u8()]
        fill = r.u8()
        shape = r.dims()
        fn = np.ones if fill else np.zeros
        h = self._new(self.nd, array(fn(shape, dtype)))
        return struct.pack("<Q", h)

    def nd_fromdata(self, r):
        import numpy as np

        from .ndarray.ndarray import array

        dtype = np.dtype(_DTYPES[r.u8()])
        shape = r.dims()
        data = np.frombuffer(r.rest(), dtype).reshape(shape)
        h = self._new(self.nd, array(data.copy()))
        return struct.pack("<Q", h)

    def nd_tohost(self, r):
        import numpy as np

        a = self.nd[r.u64()]
        out = a.asnumpy()
        if out.dtype not in (np.float32, np.int32):
            out = out.astype(np.float32)
        return np.ascontiguousarray(out).tobytes()

    def nd_shape(self, r):
        return _shape_reply(self.nd[r.u64()].shape)

    def nd_free(self, r):
        a = self.nd.pop(r.u64(), None)
        if a is not None:
            self._nd_rev.pop(id(a), None)
        return b""

    def nd_copyfrom(self, r):
        import numpy as np

        from .base import MXNetError
        from .ndarray.ndarray import array

        a = self.nd[r.u64()]
        dtype = np.dtype(a.dtype)
        raw = r.rest()
        if len(raw) != a.size * dtype.itemsize:
            raise MXNetError("copy size mismatch: array wants %d bytes, "
                             "got %d" % (a.size * dtype.itemsize,
                                         len(raw)))
        data = np.frombuffer(raw, dtype).reshape(a.shape)
        a._rebind(array(data.copy())._data)
        return b""

    # -- imperative invoke -------------------------------------------------

    def invoke(self, r):
        from .ndarray.ndarray import _invoke_nd

        op = r.string()
        n_in = r.u32()
        ins = [self.nd[r.u64()] for _ in range(n_in)]
        attrs = {}
        for _ in range(r.u32()):
            k = r.string()
            attrs[k] = r.string()
        # registry dispatch (the c_api MXImperativeInvoke path): handles
        # mutate_inputs semantics, rng ops, and multi-output ops
        out = _invoke_nd(op, ins, attrs)
        outs = list(out) if isinstance(out, (list, tuple)) else [out]
        reply = struct.pack("<I", len(outs))
        for o in outs:
            reply += struct.pack("<Q", self._new(self.nd, o))
        return reply

    # -- symbol ------------------------------------------------------------

    def sym_fromjson(self, r):
        from .symbol import symbol as S

        sym = S.load_json(r.rest().decode("utf-8"))
        return struct.pack("<Q", self._new(self.sym, sym))

    def sym_args(self, r):
        names = self.sym[r.u64()].list_arguments()
        reply = struct.pack("<I", len(names))
        for n in names:
            b = n.encode("utf-8")
            reply += struct.pack("<I", len(b)) + b
        return reply

    def sym_infer(self, r):
        sym = self.sym[r.u64()]
        provided = {}
        for _ in range(r.u32()):
            name = r.string()
            provided[name] = r.dims()
        arg_shapes, out_shapes, _aux = sym.infer_shape(**provided)
        reply = struct.pack("<I", len(arg_shapes))
        for s in arg_shapes:
            reply += _shape_reply(s)
        reply += struct.pack("<I", len(out_shapes))
        for s in out_shapes:
            reply += _shape_reply(s)
        return reply

    def sym_free(self, r):
        self.sym.pop(r.u64(), None)
        return b""

    # -- executor ----------------------------------------------------------

    def exec_bind(self, r):
        import numpy as np

        import mxnet_tpu as mx
        from .ndarray.ndarray import array

        sym = self.sym[r.u64()]
        args = {}
        for _ in range(r.u32()):
            name = r.string()
            args[name] = self.nd[r.u64()]
        aux = {}
        for _ in range(r.u32()):
            name = r.string()
            aux[name] = self.nd[r.u64()]
        with_grad = r.u8()
        grads = {n: array(np.zeros(a.shape, np.float32))
                 for n, a in args.items()} if with_grad else None
        ctx = mx.cpu() if os.environ.get("MXTPU_API_CPU") \
            else mx.context.current_context()
        exe = sym.bind(ctx, args=args, args_grad=grads,
                       grad_req="write" if with_grad else "null",
                       aux_states=aux or None)
        return struct.pack("<Q", self._new(self.exe, exe))

    def exec_fwd(self, r):
        exe = self.exe[r.u64()]
        is_train = bool(r.u8())
        outs = exe.forward(is_train=is_train)
        reply = struct.pack("<I", len(outs))
        for o in outs:
            reply += struct.pack("<Q", self._new(self.nd, o))
        return reply

    def exec_bwd(self, r):
        exe = self.exe[r.u64()]
        n = r.u32()
        heads = [self.nd[r.u64()] for _ in range(n)]
        exe.backward(heads or None)
        return b""

    def exec_grad(self, r):
        exe = self.exe[r.u64()]
        name = r.string()
        g = exe.grad_dict.get(name)
        if g is None:
            from .base import MXNetError

            raise MXNetError("no gradient bound for %r" % name)
        # the executor rebinds this NDArray in place on every backward,
        # so one handle stays valid for the whole training run (_new
        # reuses the existing handle if the array is already tabled)
        return struct.pack("<Q", self._new(self.nd, g))

    def exec_free(self, r):
        self.exe.pop(r.u64(), None)
        return b""

    # -- misc --------------------------------------------------------------

    def seed(self, r):
        from . import random as _random

        _random.seed(r.u64())
        return b""


def main():
    fin = sys.stdin.buffer
    # the wire owns fd 1: duplicate it, then point fd 1 at stderr so
    # native-level printf (XLA/plugin logging) cannot corrupt the
    # length-prefixed protocol (same discipline as predict_worker)
    fout = os.fdopen(os.dup(1), "wb")
    os.dup2(2, 1)
    sys.stdout = sys.stderr
    srv = _Server()
    ops = {1: srv.nd_create, 2: srv.nd_fromdata, 3: srv.nd_tohost,
           4: srv.nd_shape, 5: srv.nd_free, 6: srv.invoke,
           7: srv.sym_fromjson, 8: srv.sym_args, 9: srv.sym_infer,
           10: srv.exec_bind, 11: srv.exec_fwd, 12: srv.exec_bwd,
           13: srv.exec_grad, 14: srv.seed, 15: srv.sym_free,
           16: srv.exec_free, 17: srv.nd_copyfrom}
    while True:
        try:
            head = _read_exact(fin, 9)
        except EOFError:
            return
        opcode, plen = struct.unpack("<BQ", head)
        payload = _read_exact(fin, plen) if plen else b""
        if opcode == 0:
            return
        try:
            reply = ops[opcode](_Reader(payload))
            fout.write(struct.pack("<BQ", 0, len(reply)) + reply)
        except Exception as e:  # error reply, keep serving
            msg = ("%s: %s" % (type(e).__name__, e)).encode("utf-8")
            fout.write(struct.pack("<BQ", 1, len(msg)) + msg)
        fout.flush()


if __name__ == "__main__":
    main()
