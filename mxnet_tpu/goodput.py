"""Job-lifetime goodput ledger: cross-restart badput attribution and
preemption lost-work accounting.

Every observability layer before this PR — step-time attribution
(``perf_ledger.StepBreakdown``), wide events, the fleet observatory —
measures *within one process incarnation*.  The question an operator
of a pod-scale, preemption-surviving job actually asks spans restarts:
"what fraction of wall-clock became training progress, and where did
the rest go?"  This module answers it with a typed wall-clock ledger:

* **Recorder** — :class:`GoodputRecorder`: each process incarnation
  appends typed segments to its own JSONL file in a shared job dir
  (``MXNET_GOODPUT_DIR``).  Segment kinds: ``productive_step``,
  ``compile`` (fed by the AOT path and the jax.monitoring bridge),
  ``ckpt_save`` / ``ckpt_restore``, ``data_wait``, ``startup``,
  ``drain``.  Boundary records bracket the incarnation: an
  ``incarnation_start`` (start reason, resumed-from step) and — on a
  *clean or preempted* exit only — an ``incarnation_end``.  A SIGKILL
  leaves no end record: that absence IS the kill signal the reader
  prices.  Durability follows the fleet-spool sidecar discipline bent
  to an append-only file: records land with single ``O_APPEND``
  writes, and a ``<ledger>.ok`` sidecar carries ``{bytes, sha256}`` of
  the flushed *prefix* — sidecar-verified prefix == durable, while the
  unflushed tail is still parsed best-effort under the ``read_ledger``
  torn-line discipline (counted problem per bad line, never a crash),
  so a killed incarnation's last seconds still count.
* **Reader** — :func:`read_job` / :func:`goodputz`: merges every
  incarnation of every rank in the job dir into one report.  The
  lost-work rule: in a killed incarnation, steps completed after the
  last *committed* checkpoint (``ckpt_save`` with ``committed``, else
  the resumed-from step) are badput — priced at that incarnation's own
  measured seconds-per-step and moved from ``goodput`` into
  ``lost_work``.  ``other`` absorbs wall time no segment claimed, so
  the buckets sum to wall-clock by construction (the tier-1
  invariant).  MTTR pairs each kill with the first productive step of
  the same rank's successor incarnation.

Serving surfaces: ``tools/goodputz.py`` (CLI), the ``/goodputz``
scrape route, a ``goodput`` /statusz subsystem, a heartbeat
``goodput`` field, ``perf_report --goodput``, and a per-rank
``goodput_pct`` column in the merged ``/fleetz`` pod view (the
snapshot's statusz carries this module's summary).

STDLIB-ONLY AT IMPORT by contract (like ``fleet``/``perf_ledger``):
tools load this file standalone, so every ``mxnet_tpu`` reference is a
lazy lookup and the telemetry counters fire only when the package is
already loaded.  See docs/observability.md "Goodput ledger".
"""
from __future__ import annotations

import hashlib
import json
import logging
import os
import re
import sys
import tempfile
import threading
import time

__all__ = ["GoodputRecorder", "SEGMENT_KINDS", "BUCKETS",
           "set_dir", "active_dir", "active", "record_segment",
           "record_compile", "compile_guard", "note_exit",
           "read_job", "goodputz", "render_report", "ledger_records",
           "status_summary", "heartbeat_fields",
           "LEDGER_NAME", "SIDECAR_SUFFIX"]

logger = logging.getLogger("mxnet_tpu.goodput")

FORMAT_VERSION = 1

LEDGER_NAME = "goodput-r%05d-%s.jsonl"
SIDECAR_SUFFIX = ".ok"
_LEDGER_RE = re.compile(r"^goodput-r(\d{5})-([0-9a-f]+)\.jsonl$")

#: the typed segment taxonomy (docs/observability.md "Goodput ledger")
SEGMENT_KINDS = ("productive_step", "compile", "ckpt_save",
                 "ckpt_restore", "data_wait", "startup", "drain")

#: report buckets: goodput + the badput decomposition.  ``lost_work``
#: is carved out of ``productive_step`` by the pricing rule; ``other``
#: is wall time no segment claimed (sum-to-wall by construction).
BUCKETS = ("goodput", "lost_work", "compile", "ckpt_save",
           "ckpt_restore", "data_wait", "startup", "drain", "other")

_PROCESS_START = time.time()   # default epoch for the startup segment
_LAST_END = None               # when a prior recorder in THIS process
# ended: the successor's default startup epoch, so back-to-back
# incarnations tile the process wall instead of overlapping it
_compile_total = 0.0           # see compile_seconds_total()


# ---------------------------------------------------------------------------
# lazy package hooks (the stdlib-only-at-import contract, as fleet.py)
# ---------------------------------------------------------------------------

def _flag(name, default):
    """Config knob via mxnet_tpu.config when the package is loaded,
    raw env otherwise (tools load this file standalone)."""
    cfg = sys.modules.get("mxnet_tpu.config")
    if cfg is not None:
        try:
            return cfg.get(name)
        except Exception:
            pass
    raw = os.environ.get(name, default)
    if isinstance(default, (int, float)) and not isinstance(default, bool):
        try:
            return type(default)(float(raw))
        except (TypeError, ValueError):
            return default
    return raw


def _tel():
    """The live telemetry module when the package already imported it,
    else None (a standalone reader has no registry to count into)."""
    return sys.modules.get("mxnet_tpu.telemetry")


def _atomic_write(path, data):
    """Atomic tmp+fsync+rename (checkpoint.atomic_write when the
    package is loaded; local fallback keeps standalone readers free)."""
    ck = sys.modules.get("mxnet_tpu.checkpoint")
    if ck is not None:
        ck.atomic_write(path, data)
        return
    if isinstance(data, str):
        data = data.encode("utf-8")
    dirname = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(dir=dirname,
                               prefix=os.path.basename(path) + ".",
                               suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _proc_identity():
    """(rank, n_procs) from the distributed env (0/1 single-process)."""
    try:
        rank = int(_flag("MXNET_DIST_PROC_ID", -1))
    except (TypeError, ValueError):
        rank = -1
    try:
        n = int(_flag("MXNET_DIST_NUM_PROCS", 0))
    except (TypeError, ValueError):
        n = 0
    return (rank if rank >= 0 else 0), (n if n > 1 else 1)


# ---------------------------------------------------------------------------
# job-dir activation
# ---------------------------------------------------------------------------

_active_dir = None       # set by GoodputRecorder.begin / set_dir()


def set_dir(path):
    """Pin the process-wide job dir (None = back to the
    ``MXNET_GOODPUT_DIR`` knob) — what the heartbeat and the
    ``/statusz``/``/goodputz`` defaults read."""
    global _active_dir
    _active_dir = os.fspath(path) if path is not None else None


def active_dir():
    """The active job dir, or None: an explicit :func:`set_dir` /
    live recorder wins, else a non-empty ``MXNET_GOODPUT_DIR``."""
    if _active_dir:
        return _active_dir
    d = _flag("MXNET_GOODPUT_DIR", "")
    return str(d) if d else None


# ---------------------------------------------------------------------------
# recorder
# ---------------------------------------------------------------------------

class GoodputRecorder:
    """One incarnation's segment recorder (append-only JSONL).

    ``rank``/``n_procs`` default to the ``MXNET_DIST_PROC_ID`` /
    ``MXNET_DIST_NUM_PROCS`` identity; ``flush_every`` (default
    ``MXNET_GOODPUT_FLUSH_EVERY``) is how many records may land
    between prefix-digest sidecar updates.  Recording never raises
    into the step loop: a failed write is counted
    (``mxnet_tpu_goodput_write_errors_total``) and logged once.
    """

    def __init__(self, dir=None, rank=None, n_procs=None,
                 flush_every=None):
        d = dir or active_dir()
        if not d:
            raise ValueError("no goodput dir: pass dir= or set "
                             "MXNET_GOODPUT_DIR")
        self.dir = os.fspath(d)
        env_rank, env_n = _proc_identity()
        self.rank = int(rank) if rank is not None else env_rank
        self.n_procs = int(n_procs) if n_procs is not None else env_n
        self.incarnation = os.urandom(6).hex()
        self.path = os.path.join(self.dir,
                                 LEDGER_NAME % (self.rank,
                                                self.incarnation))
        self.flush_every = int(flush_every) if flush_every is not None \
            else int(_flag("MXNET_GOODPUT_FLUSH_EVERY", 16))
        self._fd = None
        self._lock = threading.Lock()
        self._hash = hashlib.sha256()
        self._bytes = 0
        self._since_flush = 0
        self._warned = False
        self._ended = False

    # -- lifecycle -------------------------------------------------------
    def begin(self, start_reason="fresh", resumed_from_step=None,
              started_at=None):
        """Open the ledger, write the ``incarnation_start`` boundary
        plus the ``startup`` segment (wall since ``started_at``,
        default process start), and install this recorder as the
        process-wide producer target.  Never raises: an unwritable job
        dir leaves the recorder inactive with a counted error."""
        now = time.time()
        if started_at is not None:
            t0 = float(started_at)
        elif _LAST_END is not None:
            t0 = _LAST_END
        else:
            t0 = _PROCESS_START
        t0 = min(t0, now)
        try:
            os.makedirs(self.dir, exist_ok=True)
            self._fd = os.open(self.path,
                               os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                               0o644)
        except OSError:
            self._count_error("goodput ledger unwritable: %s" % self.path)
            return self
        self._write({
            "type": "incarnation_start",
            "format_version": FORMAT_VERSION,
            "incarnation": self.incarnation,
            "rank": self.rank,
            "n_procs": self.n_procs,
            "pid": os.getpid(),
            # stamped at the STARTUP EPOCH (process start / started_at),
            # not at begin(): the startup segment must fall inside the
            # incarnation's wall window or the buckets cannot sum to it
            "time": t0,
            "start_reason": str(start_reason),
            "resumed_from_step": (int(resumed_from_step)
                                  if resumed_from_step is not None
                                  else None),
        })
        self.segment("startup", max(0.0, now - t0))
        self.flush()
        set_dir(self.dir)
        global _recorder
        _recorder = self
        # a clean interpreter exit closes the incarnation; a SIGKILL
        # skips atexit — the missing end record IS the kill evidence,
        # and a preemption handler's earlier end() makes this a no-op
        import atexit

        atexit.register(self.end, "clean")
        return self

    def end(self, exit_reason="clean", step=None):
        """Write the ``incarnation_end`` boundary, flush the sidecar,
        close the ledger, and detach the process-wide producer target.
        A killed incarnation never gets here — the missing end record
        is what the reader prices as lost work."""
        global _recorder, _LAST_END
        if self._ended:
            return
        self._ended = True
        _LAST_END = time.time()
        self._write({
            "type": "incarnation_end",
            "time": _LAST_END,
            "exit_reason": str(exit_reason),
            "step": int(step) if step is not None else None,
        })
        self.flush()
        with self._lock:
            if self._fd is not None:
                try:
                    os.close(self._fd)
                except OSError:
                    pass
                self._fd = None
        if _recorder is self:
            _recorder = None

    # -- segments --------------------------------------------------------
    def segment(self, kind, dur_s, step=None, steps=None, **fields):
        """Append one typed wall-clock segment (best-effort)."""
        rec = {"type": "segment", "kind": str(kind),
               "dur_s": float(dur_s), "time": time.time()}
        if step is not None:
            rec["step"] = int(step)
        if steps is not None:
            rec["steps"] = int(steps)
        rec.update(fields)
        if self._write(rec):
            if kind == "compile":
                global _compile_total
                _compile_total += float(dur_s)
            tel = _tel()
            if tel is not None:
                tel.GOODPUT_SEGMENTS.inc(kind=str(kind))

    def flush(self):
        """Commit the prefix-digest sidecar: everything written so far
        is durable-marked ``{bytes, sha256}`` (atomic write)."""
        with self._lock:
            if self._fd is None:
                return
            try:
                os.fsync(self._fd)
            except OSError:
                pass
            sidecar = {"format_version": FORMAT_VERSION,
                       "bytes": self._bytes,
                       "sha256": self._hash.hexdigest(),
                       "time": time.time()}
            self._since_flush = 0
        try:
            _atomic_write(self.path + SIDECAR_SUFFIX,
                          json.dumps(sidecar, sort_keys=True))
        except Exception:
            self._count_error("goodput sidecar write failed")

    # -- internals -------------------------------------------------------
    def _write(self, rec):
        line = (json.dumps(rec, sort_keys=True, default=str) + "\n") \
            .encode("utf-8")
        need_flush = False
        with self._lock:
            if self._fd is None or (self._ended
                                    and rec.get("type") != "incarnation_end"):
                return False
            try:
                os.write(self._fd, line)
            except OSError:
                self._count_error("goodput ledger append failed")
                return False
            self._hash.update(line)
            self._bytes += len(line)
            self._since_flush += 1
            if self.flush_every > 0 and \
                    self._since_flush >= self.flush_every:
                need_flush = True
        if need_flush:
            self.flush()
        return True

    def _count_error(self, msg):
        if not self._warned:
            self._warned = True
            logger.warning("%s (counted, further errors silent)", msg)
        tel = _tel()
        if tel is not None:
            try:
                tel.GOODPUT_WRITE_ERRORS.inc()
            except Exception:
                pass


# ---------------------------------------------------------------------------
# module-level producer API (cheap no-ops while no recorder is live)
# ---------------------------------------------------------------------------

_recorder = None         # the live incarnation recorder, if any
_tls = threading.local()


def active():
    """True while a live recorder is attached (producers' cheap gate)."""
    return _recorder is not None


def record_segment(kind, dur_s, step=None, steps=None, **fields):
    """Producer hook: append a segment to the live recorder (no-op
    when none is attached; never raises)."""
    rec = _recorder
    if rec is not None:
        rec.segment(kind, dur_s, step=step, steps=steps, **fields)


class _CompileGuard:
    """While held, jax.monitoring compile durations are NOT recorded —
    the holder (the AOT miss path) owns the compile segment, so the
    backend-compile events it triggers internally don't double-count."""

    def __enter__(self):
        _tls.in_compile = getattr(_tls, "in_compile", 0) + 1
        return self

    def __exit__(self, *exc):
        _tls.in_compile = getattr(_tls, "in_compile", 1) - 1
        return False


def compile_guard():
    return _CompileGuard()


def compile_seconds_total():
    """Monotonic process-wide compile seconds recorded to the ledger.
    Trainers snapshot this around a step window and carve the delta
    out of that step's ``productive_step`` segment — a jit compile
    that fires inside a step is compile badput, not goodput, and must
    not be claimed twice."""
    return _compile_total


def record_compile(dur_s):
    """The jax.monitoring bridge's compile feed: records a ``compile``
    segment unless an AOT compile scope already owns it."""
    if getattr(_tls, "in_compile", 0):
        return
    record_segment("compile", dur_s)


def note_exit(exit_reason, step=None):
    """Producer hook: write the incarnation_end boundary (preemption
    handlers, trainer close).  No-op when no recorder is live."""
    rec = _recorder
    if rec is not None:
        rec.end(exit_reason, step=step)


# ---------------------------------------------------------------------------
# reader: ledger parsing (torn-line discipline)
# ---------------------------------------------------------------------------

def _parse_ledger(path, name):
    """(records, problems, torn) for one incarnation file.  The
    sidecar-verified prefix is the durable part; a digest mismatch is
    a counted torn problem, and the file is STILL parsed best-effort
    line-by-line (a killed incarnation's unflushed tail counts too).
    Unparsable lines are skipped with a counted problem, never a
    crash."""
    problems, torn = [], 0
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError as e:
        return [], ["%s: unreadable (%s)" % (name, e)], 1
    try:
        with open(path + SIDECAR_SUFFIX, encoding="utf-8") as f:
            sidecar = json.load(f)
    except (OSError, ValueError):
        sidecar = None   # died before the first flush — tail-only file
    if isinstance(sidecar, dict):
        try:
            n = int(sidecar.get("bytes", 0))
        except (TypeError, ValueError):
            n = 0
        if n > len(raw):
            torn += 1
            problems.append("%s: sidecar claims %d bytes, file has %d "
                            "(truncated ledger)" % (name, n, len(raw)))
        elif n > 0 and hashlib.sha256(raw[:n]).hexdigest() != \
                sidecar.get("sha256"):
            torn += 1
            problems.append("%s: durable prefix digest mismatch "
                            "(torn ledger)" % name)
    records = []
    for lineno, line in enumerate(raw.split(b"\n"), 1):
        if not line.strip():
            continue
        try:
            rec = json.loads(line.decode("utf-8"))
            if not isinstance(rec, dict):
                raise ValueError("not an object")
        except (ValueError, UnicodeDecodeError) as e:
            torn += 1
            problems.append("%s:%d: unparsable ledger line (%s) — skipped"
                            % (name, lineno, e))
            continue
        records.append(rec)
    return records, problems, torn


def _num(v, default=None):
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return default
    return float(v)


def _assemble(records, name, rank_hint):
    """Fold one file's records into an incarnation dict."""
    inc = {
        "file": name,
        "incarnation": None,
        "rank": rank_hint,
        "n_procs": 1,
        "pid": None,
        "start_time": None,
        "start_reason": "unknown",
        "resumed_from_step": None,
        "end": None,              # {"time", "exit_reason", "step"} | None
        "last_time": None,
        "segments": {},           # kind -> {"seconds", "count"}
        "steps": 0,
        "last_step": None,
        "first_step_time": None,
        "last_ckpt_step": None,
    }
    for rec in records:
        t = _num(rec.get("time"))
        if t is not None:
            if inc["last_time"] is None or t > inc["last_time"]:
                inc["last_time"] = t
            if inc["start_time"] is None or t < inc["start_time"]:
                inc["start_time"] = t
        rtype = rec.get("type")
        if rtype == "incarnation_start":
            inc["incarnation"] = rec.get("incarnation") or inc["incarnation"]
            if isinstance(rec.get("rank"), int):
                inc["rank"] = rec["rank"]
            if isinstance(rec.get("n_procs"), int):
                inc["n_procs"] = rec["n_procs"]
            inc["pid"] = rec.get("pid", inc["pid"])
            inc["start_reason"] = str(rec.get("start_reason", "unknown"))
            rf = rec.get("resumed_from_step")
            if isinstance(rf, int):
                inc["resumed_from_step"] = rf
            if t is not None:
                inc["start_time"] = min(inc["start_time"], t)
        elif rtype == "incarnation_end":
            inc["end"] = {"time": t,
                          "exit_reason": str(rec.get("exit_reason",
                                                     "unknown")),
                          "step": rec.get("step")}
        elif rtype == "segment":
            kind = str(rec.get("kind", "other"))
            dur = _num(rec.get("dur_s"))
            if dur is None or dur < 0:
                continue
            row = inc["segments"].setdefault(kind,
                                             {"seconds": 0.0, "count": 0})
            row["seconds"] += dur
            row["count"] += 1
            if kind == "productive_step":
                inc["steps"] += int(rec.get("steps", 1) or 1)
                step = rec.get("step")
                if isinstance(step, int):
                    if inc["last_step"] is None or step > inc["last_step"]:
                        inc["last_step"] = step
                if t is not None and (inc["first_step_time"] is None
                                      or t < inc["first_step_time"]):
                    inc["first_step_time"] = t
            elif kind == "ckpt_save" and rec.get("committed"):
                step = rec.get("step")
                if isinstance(step, int) and \
                        (inc["last_ckpt_step"] is None
                         or step > inc["last_ckpt_step"]):
                    inc["last_ckpt_step"] = step
    return inc


def read_job(job_dir):
    """Parse every incarnation ledger under ``job_dir``.

    Returns ``{"incarnations": [inc], "problems": [str],
    "torn_lines": n}`` — incarnations sorted by (rank, start time).
    Never raises on ledger content; torn lines/files are counted
    (``mxnet_tpu_goodput_torn_lines_total``) and listed."""
    incs, problems, torn = [], [], 0
    try:
        names = sorted(os.listdir(job_dir))
    except OSError as e:
        return {"incarnations": [],
                "problems": ["%s: cannot list job dir (%s)"
                             % (job_dir, e)],
                "torn_lines": 0}
    for name in names:
        m = _LEDGER_RE.match(name)
        if not m:
            continue
        records, probs, t = _parse_ledger(os.path.join(job_dir, name),
                                          name)
        problems.extend(probs)
        torn += t
        if not records:
            continue
        inc = _assemble(records, name, int(m.group(1)))
        if inc["incarnation"] is None:
            inc["incarnation"] = m.group(2)
        incs.append(inc)
    incs.sort(key=lambda i: (i["rank"], i["start_time"] or 0.0,
                             i["file"]))
    tel = _tel()
    if tel is not None and torn:
        try:
            tel.GOODPUT_TORN_LINES.inc(torn)
        except Exception:
            pass
    return {"incarnations": incs, "problems": problems,
            "torn_lines": torn}


# ---------------------------------------------------------------------------
# reader: pricing + the /goodputz payload
# ---------------------------------------------------------------------------

def _price(inc):
    """One incarnation's bucket decomposition (the lost-work rule).

    killed  = no incarnation_end record.
    baseline = last committed ckpt_save step in this incarnation,
               else the resumed-from step, else 0 (a fresh start).
    lost_steps = steps completed past the baseline; priced at THIS
    incarnation's measured seconds-per-step and moved from goodput to
    lost_work.  ``other`` = wall the segments didn't claim, so the
    buckets sum to wall by construction."""
    seg_s = {k: v["seconds"] for k, v in inc["segments"].items()}
    productive_s = seg_s.get("productive_step", 0.0)
    steps = inc["steps"]
    killed = inc["end"] is None
    baseline = 0
    if inc["resumed_from_step"] is not None:
        baseline = inc["resumed_from_step"]
    if inc["last_ckpt_step"] is not None:
        baseline = max(baseline, inc["last_ckpt_step"])
    lost_steps = 0
    if killed and inc["last_step"] is not None:
        lost_steps = max(0, inc["last_step"] - baseline)
    per_step = (productive_s / steps) if steps > 0 else 0.0
    lost_work_s = min(productive_s, lost_steps * per_step)
    wall = 0.0
    if inc["start_time"] is not None and inc["last_time"] is not None:
        wall = max(0.0, inc["last_time"] - inc["start_time"])
    buckets = {b: 0.0 for b in BUCKETS}
    buckets["goodput"] = productive_s - lost_work_s
    buckets["lost_work"] = lost_work_s
    claimed = productive_s
    for kind in SEGMENT_KINDS:
        if kind == "productive_step":
            continue
        s = seg_s.get(kind, 0.0)
        buckets[kind] = s
        claimed += s
    buckets["other"] = max(0.0, wall - claimed)
    exit_reason = "killed" if killed else inc["end"]["exit_reason"]
    return {
        "incarnation": inc["incarnation"],
        "rank": inc["rank"],
        "pid": inc["pid"],
        "start_time": inc["start_time"],
        "start_reason": inc["start_reason"],
        "resumed_from_step": inc["resumed_from_step"],
        "exit_reason": exit_reason,
        "wall_s": round(wall, 6),
        "steps": steps,
        "step_time_s": round(per_step, 6),
        "last_step": inc["last_step"],
        "last_ckpt_step": inc["last_ckpt_step"],
        "lost_steps": lost_steps,
        "lost_work_s": round(lost_work_s, 6),
        "goodput_s": round(buckets["goodput"], 6),
        "buckets_s": {b: round(v, 6) for b, v in buckets.items()},
        "first_step_time": inc["first_step_time"],
        "last_time": inc["last_time"],
    }


def _mttr(rows):
    """Kill→recovery pairs: for each killed incarnation, the wall
    between its last ledger record and the first productive step of
    the same rank's next incarnation."""
    events = []
    by_rank = {}
    for r in rows:
        by_rank.setdefault(r["rank"], []).append(r)
    for rank, rs in sorted(by_rank.items()):
        rs.sort(key=lambda r: r["start_time"] or 0.0)
        for i, r in enumerate(rs):
            if r["exit_reason"] != "killed" or i + 1 >= len(rs):
                continue
            nxt = rs[i + 1]
            t0, t1 = r["last_time"], nxt["first_step_time"]
            if t0 is None or t1 is None:
                continue
            events.append({"rank": rank,
                           "killed": r["incarnation"],
                           "resumed": nxt["incarnation"],
                           "mttr_s": round(max(0.0, t1 - t0), 6)})
    mean = round(sum(e["mttr_s"] for e in events) / len(events), 6) \
        if events else None
    return {"events": events, "mean_s": mean}


def goodputz(dir=None):
    """The full job-lifetime goodput report (the ``/goodputz``
    endpoint body and the ``tools/goodputz.py`` payload): job totals,
    the bucket decomposition, the per-incarnation table, MTTR, and
    the torn-line count.  Never raises on ledger content; returns
    ``{"active": False, ...}`` when no job dir is configured."""
    d = dir or active_dir()
    if not d:
        return {"active": False,
                "error": "no goodput dir configured "
                         "(MXNET_GOODPUT_DIR or GoodputRecorder)"}
    if not os.path.isdir(d):
        return {"active": False, "dir": str(d),
                "error": "goodput dir does not exist"}
    job = read_job(d)
    rows = [_price(inc) for inc in job["incarnations"]]
    totals = {b: 0.0 for b in BUCKETS}
    wall = 0.0
    steps = lost_steps = 0
    kills = 0
    for r in rows:
        wall += r["wall_s"]
        steps += r["steps"]
        lost_steps += r["lost_steps"]
        if r["exit_reason"] == "killed":
            kills += 1
        for b in BUCKETS:
            totals[b] += r["buckets_s"].get(b, 0.0)
    goodput_s = totals["goodput"]
    pct = round(100.0 * goodput_s / wall, 2) if wall > 0 else None
    for r in rows:
        r["goodput_pct"] = round(100.0 * r["goodput_s"] / r["wall_s"], 2) \
            if r["wall_s"] > 0 else None
    return {
        "active": True,
        "format_version": FORMAT_VERSION,
        "time": round(time.time(), 3),
        "dir": str(d),
        "wall_s": round(wall, 6),
        "goodput_s": round(goodput_s, 6),
        "goodput_pct": pct,
        "badput_s": round(max(0.0, wall - goodput_s), 6),
        "buckets_s": {b: round(v, 6) for b, v in totals.items()},
        "steps": steps,
        "lost_steps": lost_steps,
        "kills": kills,
        "n_ranks": len({r["rank"] for r in rows}),
        "n_incarnations": len(rows),
        "mttr": _mttr(rows),
        "torn_lines": job["torn_lines"],
        "problems": job["problems"],
        "incarnations": rows,
    }


def render_report(payload):
    """Human rendering of a :func:`goodputz` payload (one string)."""
    if not payload.get("active"):
        return "goodput: inactive (%s)" % payload.get("error", "?")
    lines = []
    pct = payload.get("goodput_pct")
    lines.append("goodput report: dir=%s" % payload["dir"])
    lines.append("  wall %.3fs  goodput %.3fs (%s)  steps %d  "
                 "lost_steps %d  kills %d  incarnations %d/%d rank(s)"
                 % (payload["wall_s"], payload["goodput_s"],
                    ("%.2f%%" % pct) if pct is not None else "n/a",
                    payload["steps"], payload["lost_steps"],
                    payload["kills"], payload["n_incarnations"],
                    payload["n_ranks"]))
    if payload["torn_lines"]:
        lines.append("  torn_lines %d (see problems)"
                     % payload["torn_lines"])
    wall = payload["wall_s"] or 0.0
    lines.append("  %-14s %10s %8s" % ("bucket", "seconds", "% wall"))
    for b in BUCKETS:
        v = payload["buckets_s"].get(b, 0.0)
        share = (100.0 * v / wall) if wall > 0 else 0.0
        lines.append("  %-14s %10.3f %7.2f%%" % (b, v, share))
    lines.append("  incarnations:")
    lines.append("    %-5s %-12s %-7s %-8s %6s %8s %-8s %s"
                 % ("rank", "incarnation", "start", "resume@", "steps",
                    "step_s", "exit", "lost"))
    for r in payload["incarnations"]:
        resume = str(r["resumed_from_step"]) \
            if r["resumed_from_step"] is not None else "-"
        lost = "%d (%.3fs)" % (r["lost_steps"], r["lost_work_s"]) \
            if r["lost_steps"] else "-"
        lines.append("    %-5d %-12s %-7s %-8s %6d %8.4f %-8s %s"
                     % (r["rank"], str(r["incarnation"])[:12],
                        r["start_reason"], resume, r["steps"],
                        r["step_time_s"], r["exit_reason"], lost))
    m = payload["mttr"]
    if m["events"]:
        lines.append("  mttr: mean %.3fs over %d restart(s)"
                     % (m["mean_s"], len(m["events"])))
    for p in payload["problems"]:
        lines.append("  problem: %s" % p)
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# perf-ledger bridge (perf_report --goodput; bench runs)
# ---------------------------------------------------------------------------

def _perf_ledger():
    pl = sys.modules.get("mxnet_tpu.perf_ledger")
    if pl is not None:
        return pl
    import importlib.util

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "perf_ledger.py")
    spec = importlib.util.spec_from_file_location("mxnet_tpu.perf_ledger",
                                                  path)
    pl = importlib.util.module_from_spec(spec)
    sys.modules["mxnet_tpu.perf_ledger"] = pl
    spec.loader.exec_module(pl)
    return pl


def ledger_records(payload, run_id=None):
    """Schema-valid perf-ledger records from a :func:`goodputz`
    payload: ``goodput_pct`` (up-good — perf_gate knows), plus the
    lost-work and MTTR scalars, each carrying the bucket decomposition
    as extra fields.  Empty when the payload is inactive or has no
    wall-clock yet."""
    if not payload.get("active") or not payload.get("wall_s"):
        return []
    pl = _perf_ledger()
    extra = {
        "goodput_dir": payload.get("dir"),
        "goodput_buckets_s": payload.get("buckets_s"),
        "n_incarnations": payload.get("n_incarnations"),
        "kills": payload.get("kills"),
    }
    recs = []
    if payload.get("goodput_pct") is not None:
        recs.append(pl.make_record("goodput_pct",
                                   payload["goodput_pct"], "pct",
                                   run_id=run_id, **extra))
    recs.append(pl.make_record("goodput_lost_work_s",
                               payload["buckets_s"].get("lost_work", 0.0),
                               "s", run_id=run_id,
                               lost_steps=payload.get("lost_steps")))
    mean = (payload.get("mttr") or {}).get("mean_s")
    if mean is not None:
        recs.append(pl.make_record("goodput_mttr_s", mean, "s",
                                   run_id=run_id,
                                   restarts=len(payload["mttr"]["events"])))
    return recs


# ---------------------------------------------------------------------------
# serving surfaces: /statusz subsystem + heartbeat field
# ---------------------------------------------------------------------------

def status_summary():
    """The ``goodput`` subsystem of ``/statusz``: job totals only (the
    per-incarnation table is the ``/goodputz`` payload).  Reads every
    ledger in the job dir — cheap at job scale, not per-step."""
    d = active_dir()
    if not d or not os.path.isdir(d):
        return {"active": False}
    p = goodputz(d)
    if not p.get("active"):
        return {"active": False}
    return {
        "active": True,
        "dir": p["dir"],
        "goodput_pct": p["goodput_pct"],
        "wall_s": p["wall_s"],
        "lost_work_s": p["buckets_s"]["lost_work"],
        "lost_steps": p["lost_steps"],
        "kills": p["kills"],
        "n_incarnations": p["n_incarnations"],
        "torn_lines": p["torn_lines"],
    }


def heartbeat_fields():
    """{"goodput_pct"} for the heartbeat line, or None while no job
    dir is active / no wall-clock has accrued yet."""
    d = active_dir()
    if not d or not os.path.isdir(d):
        return None
    p = goodputz(d)
    if not p.get("active") or p.get("goodput_pct") is None:
        return None
    return {"goodput_pct": p["goodput_pct"]}


def _maybe_register_statusz():
    """Register the ``goodput`` /statusz subsystem when this module
    runs inside the package (a standalone tool load has no registry)."""
    tel = _tel()
    if tel is not None:
        try:
            tel.register_status_provider("goodput", status_summary)
        except Exception:
            pass


_maybe_register_statusz()
