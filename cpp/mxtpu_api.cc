// C train/NDArray ABI implementation (see mxtpu_api.h).
//
// Reference parity: the core of src/c_api/c_api.cc.  One session = one
// forked `python -m mxnet_tpu.api_worker` holding the ndarray/symbol/
// executor tables; every call is one length-prefixed round-trip
// (protocol documented in that module).  Same worker-process design as
// the predict ABI: no libpython linkage, crash isolation, IPC cost is
// noise next to the XLA compute.

#include "mxtpu_api.h"

#include <signal.h>
#include <sys/wait.h>

#include <string>
#include <vector>

#include "mxtpu_ipc.h"

namespace {

using mxtpu_ipc::append_u32;
using mxtpu_ipc::append_u64;
using mxtpu_ipc::parse_u32;
using mxtpu_ipc::parse_u64;

thread_local std::string g_last_error;

struct Session {
  mxtpu_ipc::Worker w;
};

bool call(Session *s, uint8_t op, const std::string &payload,
          std::string *reply) {
  return mxtpu_ipc::roundtrip(s->w, op, payload, reply, &g_last_error,
                              "api");
}

void append_str(std::string *p, const char *s) {
  uint32_t n = static_cast<uint32_t>(strlen(s));
  append_u32(p, n);
  p->append(s, n);
}

bool reply_handle(const std::string &reply, MXTPUHandle *out) {
  if (reply.size() != 8) {
    g_last_error = "api worker protocol corrupt (handle reply)";
    return false;
  }
  *out = parse_u64(reply.data());
  return true;
}

// parse a u32-count-prefixed handle list into out (capped)
bool reply_handles(const std::string &reply, MXTPUHandle *out,
                   uint32_t cap, uint32_t *n_out) {
  if (reply.size() < 4) {
    g_last_error = "api worker protocol corrupt (handle list)";
    return false;
  }
  uint32_t n = parse_u32(reply.data());
  if (reply.size() != 4 + 8ull * n || n > 65536) {
    g_last_error = "api worker protocol corrupt (handle list)";
    return false;
  }
  if (n > cap) {
    g_last_error = "output handle buffer too small";
    return false;
  }
  for (uint32_t i = 0; i < n; ++i)
    out[i] = parse_u64(reply.data() + 4 + 8ull * i);
  *n_out = n;
  return true;
}

}  // namespace

extern "C" {

const char *mxtpu_api_last_error(void) { return g_last_error.c_str(); }

int MXTPUSessionCreate(MXTPUSessionHandle *out) {
  Session *s = new Session();
  if (!mxtpu_ipc::spawn_worker("mxnet_tpu.api_worker", &s->w,
                               &g_last_error)) {
    delete s;
    return -1;
  }
  *out = s;
  return 0;
}

int MXTPUSessionFree(MXTPUSessionHandle sess) {
  Session *s = static_cast<Session *>(sess);
  if (!s) return 0;
  mxtpu_ipc::shutdown_worker(&s->w);
  delete s;
  return 0;
}

/* -- ndarray ------------------------------------------------------------ */

int MXTPUNDArrayCreate(MXTPUSessionHandle sess, const uint32_t *dims,
                       uint32_t ndim, int dtype, int ones,
                       MXTPUHandle *out) {
  std::string p, reply;
  p.push_back(static_cast<char>(dtype));
  p.push_back(static_cast<char>(ones ? 1 : 0));
  append_u32(&p, ndim);
  for (uint32_t i = 0; i < ndim; ++i) append_u32(&p, dims[i]);
  if (!call(static_cast<Session *>(sess), 1, p, &reply)) return -1;
  return reply_handle(reply, out) ? 0 : -1;
}

int MXTPUNDArrayFromData(MXTPUSessionHandle sess, const uint32_t *dims,
                         uint32_t ndim, int dtype, const void *data,
                         size_t nbytes, MXTPUHandle *out) {
  std::string p, reply;
  p.push_back(static_cast<char>(dtype));
  append_u32(&p, ndim);
  for (uint32_t i = 0; i < ndim; ++i) append_u32(&p, dims[i]);
  p.append(static_cast<const char *>(data), nbytes);
  if (!call(static_cast<Session *>(sess), 2, p, &reply)) return -1;
  return reply_handle(reply, out) ? 0 : -1;
}

int MXTPUNDArrayToHost(MXTPUSessionHandle sess, MXTPUHandle h, void *buf,
                       size_t nbytes) {
  std::string p, reply;
  append_u64(&p, h);
  if (!call(static_cast<Session *>(sess), 3, p, &reply)) return -1;
  if (reply.size() != nbytes) {
    g_last_error = "tensor size mismatch: worker sent " +
                   std::to_string(reply.size()) + " bytes, caller asked " +
                   std::to_string(nbytes);
    return -1;
  }
  memcpy(buf, reply.data(), nbytes);
  return 0;
}

int MXTPUNDArrayShape(MXTPUSessionHandle sess, MXTPUHandle h,
                      uint32_t *dims, uint32_t cap, uint32_t *ndim) {
  std::string p, reply;
  append_u64(&p, h);
  if (!call(static_cast<Session *>(sess), 4, p, &reply)) return -1;
  if (reply.size() < 4) {
    g_last_error = "api worker protocol corrupt (shape reply)";
    return -1;
  }
  uint32_t nd = parse_u32(reply.data());
  if (reply.size() != 4 + 4ull * nd || nd > 64) {
    g_last_error = "api worker protocol corrupt (shape reply)";
    return -1;
  }
  *ndim = nd;
  if (nd > cap) {
    g_last_error = "shape buffer too small";
    return -1;
  }
  for (uint32_t i = 0; i < nd; ++i)
    dims[i] = parse_u32(reply.data() + 4 + 4ull * i);
  return 0;
}

int MXTPUNDArrayCopyFromCPU(MXTPUSessionHandle sess, MXTPUHandle h,
                            const void *data, size_t nbytes) {
  std::string p;
  append_u64(&p, h);
  p.append(static_cast<const char *>(data), nbytes);
  return call(static_cast<Session *>(sess), 17, p, nullptr) ? 0 : -1;
}

int MXTPUNDArrayFree(MXTPUSessionHandle sess, MXTPUHandle h) {
  std::string p;
  append_u64(&p, h);
  return call(static_cast<Session *>(sess), 5, p, nullptr) ? 0 : -1;
}

/* -- imperative invoke -------------------------------------------------- */

int MXTPUImperativeInvoke(MXTPUSessionHandle sess, const char *op,
                          uint32_t n_in, const MXTPUHandle *in,
                          uint32_t n_attr, const char *const *keys,
                          const char *const *vals, MXTPUHandle *out,
                          uint32_t out_cap, uint32_t *n_out) {
  std::string p, reply;
  append_str(&p, op);
  append_u32(&p, n_in);
  for (uint32_t i = 0; i < n_in; ++i) append_u64(&p, in[i]);
  append_u32(&p, n_attr);
  for (uint32_t i = 0; i < n_attr; ++i) {
    append_str(&p, keys[i]);
    append_str(&p, vals[i]);
  }
  if (!call(static_cast<Session *>(sess), 6, p, &reply)) return -1;
  return reply_handles(reply, out, out_cap, n_out) ? 0 : -1;
}

/* -- symbol ------------------------------------------------------------- */

int MXTPUSymbolFromJSON(MXTPUSessionHandle sess, const char *json,
                        MXTPUHandle *out) {
  std::string reply;
  if (!call(static_cast<Session *>(sess), 7, json, &reply)) return -1;
  return reply_handle(reply, out) ? 0 : -1;
}

int MXTPUSymbolFromFile(MXTPUSessionHandle sess, const char *path,
                        MXTPUHandle *out) {
  FILE *f = fopen(path, "rb");
  if (!f) {
    g_last_error = std::string("cannot open ") + path;
    return -1;
  }
  std::string json;
  char buf[65536];
  size_t n;
  while ((n = fread(buf, 1, sizeof(buf), f)) > 0) json.append(buf, n);
  fclose(f);
  return MXTPUSymbolFromJSON(sess, json.c_str(), out);
}

int MXTPUSymbolListArguments(MXTPUSessionHandle sess, MXTPUHandle sym,
                             char *buf, size_t cap) {
  std::string p, reply;
  append_u64(&p, sym);
  if (!call(static_cast<Session *>(sess), 8, p, &reply)) return -1;
  if (reply.size() < 4) {
    g_last_error = "api worker protocol corrupt (args reply)";
    return -1;
  }
  uint32_t n = parse_u32(reply.data());
  size_t off = 4, w = 0;
  for (uint32_t i = 0; i < n; ++i) {
    if (off + 4 > reply.size()) {
      g_last_error = "api worker protocol corrupt (args reply)";
      return -1;
    }
    uint32_t len = parse_u32(reply.data() + off);
    off += 4;
    if (off + len > reply.size()) {
      g_last_error = "api worker protocol corrupt (args reply)";
      return -1;
    }
    if (w + len + 2 > cap) {
      g_last_error = "argument name buffer too small";
      return -1;
    }
    if (i) buf[w++] = '\n';
    memcpy(buf + w, reply.data() + off, len);
    w += len;
    off += len;
  }
  // the loop's per-name check reserves NUL space only when n > 0; a
  // zero-argument symbol reaches here with w == 0 and an unchecked
  // write would be out of bounds for cap == 0
  if (w >= cap) {
    g_last_error = "argument name buffer too small";
    return -1;
  }
  buf[w] = '\0';
  return 0;
}

int MXTPUSymbolInferShape(MXTPUSessionHandle sess, MXTPUHandle sym,
                          uint32_t n_provided, const char *const *names,
                          const uint32_t *ndims,
                          const uint32_t *dims_concat,
                          uint32_t *arg_ndims, uint32_t arg_cap,
                          uint32_t *arg_dims_concat,
                          uint32_t arg_dims_cap, uint32_t *n_args,
                          uint32_t *out_ndims, uint32_t out_cap,
                          uint32_t *out_dims_concat,
                          uint32_t out_dims_cap, uint32_t *n_outputs) {
  std::string p, reply;
  append_u64(&p, sym);
  append_u32(&p, n_provided);
  const uint32_t *d = dims_concat;
  for (uint32_t i = 0; i < n_provided; ++i) {
    append_str(&p, names[i]);
    append_u32(&p, ndims[i]);
    for (uint32_t k = 0; k < ndims[i]; ++k) append_u32(&p, *d++);
  }
  if (!call(static_cast<Session *>(sess), 9, p, &reply)) return -1;

  size_t off = 0;
  g_last_error.clear();  // so the generic fallback below can detect
                         // whether take_group set a specific message
  auto take_group = [&](uint32_t *ndims_out, uint32_t entry_cap,
                        uint32_t *dims_out, uint32_t dims_cap,
                        uint32_t *count) {
    if (off + 4 > reply.size()) return false;
    uint32_t n = parse_u32(reply.data() + off);
    off += 4;
    // entry count is attacker/worker-controlled: bound it by the
    // caller's buffer BEFORE any write (stack-smash guard)
    if (n > entry_cap) {
      g_last_error = "infer-shape buffers too small (need " +
                     std::to_string(n) + " entries)";
      return false;
    }
    uint32_t written = 0;
    for (uint32_t i = 0; i < n; ++i) {
      if (off + 4 > reply.size()) return false;
      uint32_t nd = parse_u32(reply.data() + off);
      off += 4;
      if (off + 4ull * nd > reply.size() || nd > 64) return false;
      ndims_out[i] = nd;
      for (uint32_t k = 0; k < nd; ++k) {
        if (written >= dims_cap) return false;
        dims_out[written] = parse_u32(reply.data() + off);
        ++written;
        off += 4;
      }
    }
    *count = n;
    return true;
  };
  if (!take_group(arg_ndims, arg_cap, arg_dims_concat, arg_dims_cap,
                  n_args) ||
      !take_group(out_ndims, out_cap, out_dims_concat, out_dims_cap,
                  n_outputs)) {
    if (g_last_error.empty())
      g_last_error = "api worker protocol corrupt (infer-shape reply)";
    return -1;
  }
  return 0;
}

int MXTPUSymbolFree(MXTPUSessionHandle sess, MXTPUHandle sym) {
  std::string p;
  append_u64(&p, sym);
  return call(static_cast<Session *>(sess), 15, p, nullptr) ? 0 : -1;
}

/* -- executor ----------------------------------------------------------- */

int MXTPUExecutorBind(MXTPUSessionHandle sess, MXTPUHandle sym,
                      uint32_t n_args, const char *const *arg_names,
                      const MXTPUHandle *arg_handles, uint32_t n_aux,
                      const char *const *aux_names,
                      const MXTPUHandle *aux_handles, int with_grad,
                      MXTPUHandle *out) {
  std::string p, reply;
  append_u64(&p, sym);
  append_u32(&p, n_args);
  for (uint32_t i = 0; i < n_args; ++i) {
    append_str(&p, arg_names[i]);
    append_u64(&p, arg_handles[i]);
  }
  append_u32(&p, n_aux);
  for (uint32_t i = 0; i < n_aux; ++i) {
    append_str(&p, aux_names[i]);
    append_u64(&p, aux_handles[i]);
  }
  p.push_back(static_cast<char>(with_grad ? 1 : 0));
  if (!call(static_cast<Session *>(sess), 10, p, &reply)) return -1;
  return reply_handle(reply, out) ? 0 : -1;
}

int MXTPUExecutorForward(MXTPUSessionHandle sess, MXTPUHandle exec,
                         int is_train, MXTPUHandle *outputs,
                         uint32_t cap, uint32_t *n_out) {
  std::string p, reply;
  append_u64(&p, exec);
  p.push_back(static_cast<char>(is_train ? 1 : 0));
  if (!call(static_cast<Session *>(sess), 11, p, &reply)) return -1;
  return reply_handles(reply, outputs, cap, n_out) ? 0 : -1;
}

int MXTPUExecutorBackward(MXTPUSessionHandle sess, MXTPUHandle exec,
                          uint32_t n_heads, const MXTPUHandle *heads) {
  std::string p;
  append_u64(&p, exec);
  append_u32(&p, n_heads);
  for (uint32_t i = 0; i < n_heads; ++i) append_u64(&p, heads[i]);
  return call(static_cast<Session *>(sess), 12, p, nullptr) ? 0 : -1;
}

int MXTPUExecutorArgGrad(MXTPUSessionHandle sess, MXTPUHandle exec,
                         const char *arg_name, MXTPUHandle *out) {
  std::string p, reply;
  append_u64(&p, exec);
  append_str(&p, arg_name);
  if (!call(static_cast<Session *>(sess), 13, p, &reply)) return -1;
  return reply_handle(reply, out) ? 0 : -1;
}

int MXTPUExecutorFree(MXTPUSessionHandle sess, MXTPUHandle exec) {
  std::string p;
  append_u64(&p, exec);
  return call(static_cast<Session *>(sess), 16, p, nullptr) ? 0 : -1;
}

/* -- misc --------------------------------------------------------------- */

int MXTPURandomSeed(MXTPUSessionHandle sess, uint64_t seed) {
  std::string p;
  append_u64(&p, seed);
  return call(static_cast<Session *>(sess), 14, p, nullptr) ? 0 : -1;
}

}  // extern "C"
