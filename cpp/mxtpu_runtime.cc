// mxtpu native runtime: the C++ components of the TPU-native framework.
//
// Reference parity niches (cnzhanj/incubator-mxnet):
//  - src/io/iter_image_recordio_2.cc : the threaded RecordIO -> JPEG ->
//    batch pipeline.  Here: record index scan, pread-based record
//    fetch (thread safe, no fd seek races), and a libjpeg batch
//    decoder that runs on std::thread workers -- fully outside the
//    Python GIL.
//  - src/storage/ (pooled_memory_storage) : a size-bucketed buffer
//    pool with allocation statistics, backing the IO pipeline's batch
//    staging buffers.
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in this
// environment).  Build: see cpp/Makefile (g++ -O2 -shared -fPIC,
// linked against the system libjpeg).
#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <mutex>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <unistd.h>

#include <jpeglib.h>
#include <setjmp.h>

extern "C" {

// ---------------------------------------------------------------------------
// RecordIO (format: little-endian u32 magic 0xCED7230A, u32 lrec =
// cflag<<29 | length, payload, pad to 4)
// ---------------------------------------------------------------------------

static const uint32_t kMagic = 0xCED7230A;

struct MXTPURecordFile {
  int fd;
};

void* mxtpu_recordio_open(const char* path) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  return new MXTPURecordFile{fd};
}

void mxtpu_recordio_close(void* handle) {
  if (!handle) return;
  auto* f = static_cast<MXTPURecordFile*>(handle);
  ::close(f->fd);
  delete f;
}

// Scan the file once, writing each record's byte offset into out_pos.
// Returns the number of records (may exceed cap; only cap offsets are
// stored), or -1 on a framing error.
int64_t mxtpu_recordio_index(const char* path, int64_t* out_pos,
                             int64_t cap) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return -1;
  int64_t pos = 0, n = 0;
  uint32_t hdr[2];
  while (true) {
    ssize_t got = ::pread(fd, hdr, 8, pos);
    if (got < 8) break;
    if (hdr[0] != kMagic) { ::close(fd); return -1; }
    if (n < cap) out_pos[n] = pos;
    ++n;
    int64_t len = hdr[1] & ((1u << 29) - 1);
    pos += 8 + len + ((4 - (len % 4)) % 4);
  }
  ::close(fd);
  return n;
}

// Read the record at `pos` into buf (cap bytes).  Returns payload
// length (even if > cap: caller re-sizes and retries), or -1 on error.
int64_t mxtpu_recordio_read_at(void* handle, int64_t pos, uint8_t* buf,
                               int64_t cap) {
  auto* f = static_cast<MXTPURecordFile*>(handle);
  uint32_t hdr[2];
  if (::pread(f->fd, hdr, 8, pos) < 8 || hdr[0] != kMagic) return -1;
  int64_t len = hdr[1] & ((1u << 29) - 1);
  if (len <= cap && ::pread(f->fd, buf, len, pos + 8) < len) return -1;
  return len;
}

void* mxtpu_pool_alloc(int64_t size);
void mxtpu_pool_release(void* ptr, int64_t size);

// ---------------------------------------------------------------------------
// libjpeg decode (error handling via setjmp, libjpeg idiom)
// ---------------------------------------------------------------------------

struct JpegErr {
  jpeg_error_mgr mgr;
  jmp_buf jump;
};

static void jpeg_err_exit(j_common_ptr cinfo) {
  auto* err = reinterpret_cast<JpegErr*>(cinfo->err);
  longjmp(err->jump, 1);
}

// Decode one JPEG into an RGB HWC uint8 buffer of exactly h*w*3 bytes
// by center-cropping.  Sources smaller than the target return -2 (the
// caller falls back to the Python path, whose resize-then-crop
// semantics we must not silently diverge from).  Returns 0 on success.
// NOTE: no C++ objects with destructors may be live across setjmp —
// the row buffer is raw malloc, freed on both exits (longjmp rule).
static int decode_jpeg_rgb(const uint8_t* data, int64_t size,
                           uint8_t* out, int out_h, int out_w) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  uint8_t* row = nullptr;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = jpeg_err_exit;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    ::free(row);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, data, size);
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);
  const int w = cinfo.output_width, h = cinfo.output_height;
  if (w < out_w || h < out_h) {
    jpeg_abort_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);
    return -2;
  }
  row = static_cast<uint8_t*>(::malloc(static_cast<size_t>(w) * 3));
  JSAMPROW rowp = row;
  const int y_off = (h - out_h) / 2;
  const int x_off = (w - out_w) / 2;
  int y = 0;
  while (cinfo.output_scanline < cinfo.output_height) {
    jpeg_read_scanlines(&cinfo, &rowp, 1);
    const int oy = y - y_off;
    if (oy >= 0 && oy < out_h)
      std::memcpy(out + static_cast<int64_t>(oy) * out_w * 3,
                  row + static_cast<int64_t>(x_off) * 3,
                  static_cast<size_t>(out_w) * 3);
    ++y;
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  ::free(row);
  return 0;
}

// Batch pipeline step: for each of n records at positions pos[i],
// pread + parse the IRHeader (flag u32, label f32, id u64, id2 u64
// [+ flag extra label floats]) + JPEG-decode the image into
// out[i] = batch + i*out_h*out_w*3 (CHW=false: HWC layout), and write
// labels[i].  Runs on `threads` C++ threads.  Returns the number of
// failed records (their slots are zero-filled).
int64_t mxtpu_decode_batch(const char* path, const int64_t* pos,
                           int64_t n, uint8_t* batch, float* labels,
                           int out_h, int out_w, int threads) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return n;
  std::atomic<int64_t> next{0}, failed{0};
  const int64_t img_bytes = static_cast<int64_t>(out_h) * out_w * 3;

  auto worker = [&]() {
    // record staging comes from the pooled storage manager so repeated
    // batches reuse buffers instead of re-mallocing
    int64_t cap = 1 << 20;
    uint8_t* rec = static_cast<uint8_t*>(mxtpu_pool_alloc(cap));
    uint32_t hdr[2];
    while (true) {
      int64_t i = next.fetch_add(1);
      if (i >= n) break;
      uint8_t* out = batch + i * img_bytes;
      bool ok = false;
      do {
        if (::pread(fd, hdr, 8, pos[i]) < 8 || hdr[0] != kMagic) break;
        int64_t len = hdr[1] & ((1u << 29) - 1);
        if (len > cap) {
          mxtpu_pool_release(rec, cap);
          while (cap < len) cap <<= 1;
          rec = static_cast<uint8_t*>(mxtpu_pool_alloc(cap));
        }
        if (::pread(fd, rec, len, pos[i] + 8) < len) break;
        if (len < 24) break;
        uint32_t flag;
        float label;
        std::memcpy(&flag, rec, 4);
        std::memcpy(&label, rec + 4, 4);
        int64_t ir = 24 + static_cast<int64_t>(flag) * 4;
        if (flag > 0) std::memcpy(&label, rec + 24, 4);
        if (ir >= len) break;
        if (decode_jpeg_rgb(rec + ir, len - ir, out, out_h, out_w) != 0)
          break;
        labels[i] = label;
        ok = true;
      } while (false);
      if (!ok) {
        std::memset(out, 0, img_bytes);
        labels[i] = -1.0f;
        failed.fetch_add(1);
      }
    }
    mxtpu_pool_release(rec, cap);
  };

  int nt = std::max(1, threads);
  std::vector<std::thread> pool;
  pool.reserve(nt - 1);
  for (int t = 1; t < nt; ++t) pool.emplace_back(worker);
  worker();
  for (auto& th : pool) th.join();
  ::close(fd);
  return failed.load();
}

// ---------------------------------------------------------------------------
// Pooled storage manager (reference src/storage/pooled_memory_storage)
// ---------------------------------------------------------------------------

struct Pool {
  std::mutex mu;
  std::map<int64_t, std::vector<void*>> free_list;  // size -> buffers
  int64_t bytes_allocated = 0;   // live from the OS
  int64_t bytes_pooled = 0;      // idle in the free list
  int64_t n_alloc = 0, n_reuse = 0, n_free = 0;
};

static Pool g_pool;

void* mxtpu_pool_alloc(int64_t size) {
  std::lock_guard<std::mutex> lk(g_pool.mu);
  auto it = g_pool.free_list.find(size);
  if (it != g_pool.free_list.end() && !it->second.empty()) {
    void* p = it->second.back();
    it->second.pop_back();
    g_pool.bytes_pooled -= size;
    ++g_pool.n_reuse;
    return p;
  }
  void* p = ::malloc(size);
  if (p) {
    g_pool.bytes_allocated += size;
    ++g_pool.n_alloc;
  }
  return p;
}

void mxtpu_pool_release(void* ptr, int64_t size) {
  if (!ptr) return;
  std::lock_guard<std::mutex> lk(g_pool.mu);
  g_pool.free_list[size].push_back(ptr);
  g_pool.bytes_pooled += size;
  ++g_pool.n_free;
}

// stats layout: [bytes_allocated, bytes_pooled, n_alloc, n_reuse, n_free]
void mxtpu_pool_stats(int64_t* out) {
  std::lock_guard<std::mutex> lk(g_pool.mu);
  out[0] = g_pool.bytes_allocated;
  out[1] = g_pool.bytes_pooled;
  out[2] = g_pool.n_alloc;
  out[3] = g_pool.n_reuse;
  out[4] = g_pool.n_free;
}

void mxtpu_pool_clear() {
  std::lock_guard<std::mutex> lk(g_pool.mu);
  for (auto& kv : g_pool.free_list) {
    for (void* p : kv.second) {
      ::free(p);
      g_pool.bytes_allocated -= kv.first;
    }
    kv.second.clear();
  }
  g_pool.bytes_pooled = 0;
  // counters restart with the emptied pool (outstanding buffers keep
  // their bytes_allocated accounting)
  g_pool.n_alloc = g_pool.n_reuse = g_pool.n_free = 0;
}

int mxtpu_version() { return 1; }

}  // extern "C"
