/* Shared worker-process IPC layer for the mxtpu C ABIs
 * (mxtpu_predict.cc and mxtpu_api.cc).
 *
 * Framing: request = u8 opcode | u64 len | payload; response =
 * u8 status | u64 len | payload.  Integer framing fields travel
 * explicitly little-endian ('<I'/'<Q' on the python worker side) so
 * the framing survives a big-endian host; tensor payloads are shipped
 * raw (host byte order), so the full ABIs remain little-endian-host-
 * only — the explicit framing just keeps the failure mode loud
 * instead of corrupting the protocol stream.
 */
#ifndef MXTPU_IPC_H_
#define MXTPU_IPC_H_

#include <errno.h>
#include <pthread.h>
#include <signal.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>

#include <string>

namespace mxtpu_ipc {

struct Worker {
  pid_t pid = -1;
  int to_worker = -1;    // write end
  int from_worker = -1;  // read end
};

/* A dead worker must surface as EPIPE/-1, not kill the host app with
 * SIGPIPE: block the signal on this thread for the write's duration
 * and consume any pending instance. */
class ScopedSigpipeBlock {
 public:
  ScopedSigpipeBlock() {
    sigemptyset(&set_);
    sigaddset(&set_, SIGPIPE);
    blocked_ = pthread_sigmask(SIG_BLOCK, &set_, &old_) == 0;
  }
  ~ScopedSigpipeBlock() {
    if (!blocked_) return;
    struct timespec zero = {0, 0};
    while (sigtimedwait(&set_, nullptr, &zero) > 0) {
    }
    pthread_sigmask(SIG_SETMASK, &old_, nullptr);
  }

 private:
  sigset_t set_, old_;
  bool blocked_ = false;
};

inline bool write_all(int fd, const void *buf, size_t n) {
  ScopedSigpipeBlock guard;
  const char *p = static_cast<const char *>(buf);
  while (n) {
    ssize_t w = write(fd, p, n);
    if (w < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

inline bool read_all(int fd, void *buf, size_t n) {
  char *p = static_cast<char *>(buf);
  while (n) {
    ssize_t r = read(fd, p, n);
    if (r < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    if (r == 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

inline void append_u32(std::string *s, uint32_t v) {
  char b[4];
  for (int i = 0; i < 4; ++i)
    b[i] = static_cast<char>((v >> (8 * i)) & 0xff);
  s->append(b, 4);
}

inline void append_u64(std::string *s, uint64_t v) {
  char b[8];
  for (int i = 0; i < 8; ++i)
    b[i] = static_cast<char>((v >> (8 * i)) & 0xff);
  s->append(b, 8);
}

inline uint32_t parse_u32(const char *p) {
  uint32_t v = 0;
  for (int i = 0; i < 4; ++i)
    v |= static_cast<uint32_t>(static_cast<uint8_t>(p[i])) << (8 * i);
  return v;
}

inline uint64_t parse_u64(const char *p) {
  uint64_t v = 0;
  for (int i = 0; i < 8; ++i)
    v |= static_cast<uint64_t>(static_cast<uint8_t>(p[i])) << (8 * i);
  return v;
}

/* fork/exec `python -m <module>` with stdin/stdout wired to the pipes;
 * MXTPU_PYTHON overrides the interpreter. */
inline bool spawn_worker(const char *module, Worker *w,
                         std::string *err) {
  int in_pipe[2], out_pipe[2];
  if (pipe(in_pipe) != 0) {
    *err = "pipe() failed";
    return false;
  }
  if (pipe(out_pipe) != 0) {
    *err = "pipe() failed";
    close(in_pipe[0]);
    close(in_pipe[1]);
    return false;
  }
  pid_t pid = fork();
  if (pid < 0) {
    *err = "fork() failed";
    close(in_pipe[0]);
    close(in_pipe[1]);
    close(out_pipe[0]);
    close(out_pipe[1]);
    return false;
  }
  if (pid == 0) {  // child: stdin <- in_pipe, stdout -> out_pipe
    dup2(in_pipe[0], 0);
    dup2(out_pipe[1], 1);
    close(in_pipe[0]);
    close(in_pipe[1]);
    close(out_pipe[0]);
    close(out_pipe[1]);
    const char *py = getenv("MXTPU_PYTHON");
    if (!py) py = "python3";
    execlp(py, py, "-m", module, static_cast<char *>(nullptr));
    perror("execlp worker module");
    _exit(127);
  }
  close(in_pipe[0]);
  close(out_pipe[1]);
  w->pid = pid;
  w->to_worker = in_pipe[1];
  w->from_worker = out_pipe[0];
  return true;
}

/* Send the CLOSE frame, close the pipes, and reap the worker. */
inline void shutdown_worker(Worker *w) {
  if (w->to_worker >= 0) {
    char head[9] = {0};  // opcode 0 = CLOSE, zero length
    write_all(w->to_worker, head, 9);
    close(w->to_worker);
    w->to_worker = -1;
  }
  if (w->from_worker >= 0) {
    close(w->from_worker);
    w->from_worker = -1;
  }
  if (w->pid > 0) {
    int status = 0;
    waitpid(w->pid, &status, 0);
    w->pid = -1;
  }
}

/* One request/response round-trip; on failure fills *err. */
inline bool roundtrip(const Worker &w, uint8_t opcode,
                      const std::string &payload, std::string *reply,
                      std::string *err, const char *who) {
  char head[9];
  head[0] = static_cast<char>(opcode);
  uint64_t len = payload.size();
  for (int i = 0; i < 8; ++i)
    head[1 + i] = static_cast<char>((len >> (8 * i)) & 0xff);
  if (!write_all(w.to_worker, head, 9) ||
      (!payload.empty() &&
       !write_all(w.to_worker, payload.data(), payload.size()))) {
    *err = std::string(who) + " worker pipe write failed";
    return false;
  }
  char rhead[9];
  if (!read_all(w.from_worker, rhead, 9)) {
    *err = std::string(who) + " worker died (pipe closed)";
    return false;
  }
  uint8_t status = static_cast<uint8_t>(rhead[0]);
  uint64_t rlen = parse_u64(rhead + 1);
  if (rlen > (1ull << 33)) {  // corrupted frame, not a real reply
    *err = std::string(who) + " worker protocol corrupt (reply length)";
    return false;
  }
  std::string body(rlen, '\0');
  if (rlen && !read_all(w.from_worker, &body[0], rlen)) {
    *err = std::string(who) + " worker reply truncated";
    return false;
  }
  if (status != 0) {
    *err = std::string(who) + " worker error: " + body;
    return false;
  }
  if (reply) *reply = std::move(body);
  return true;
}

}  // namespace mxtpu_ipc

#endif  // MXTPU_IPC_H_
