/* C test client for the predict ABI (reference parity:
 * example/image-classification/predict-cpp/ — classify an input from
 * plain C against an exported symbol-json + .params).
 *
 * Usage: test_predict <symbol.json> <model.params> <input.f32> \
 *        <n> <c> <h> <w>
 * Prints "TOP1 <index> <score>" and the first 3 logits.
 */
#include <stdio.h>
#include <stdlib.h>

#include "mxtpu_predict.h"

static char *read_file(const char *path, size_t *len) {
  FILE *f = fopen(path, "rb");
  if (!f) {
    fprintf(stderr, "cannot open %s\n", path);
    exit(2);
  }
  fseek(f, 0, SEEK_END);
  long n = ftell(f);
  fseek(f, 0, SEEK_SET);
  char *buf = (char *)malloc((size_t)n + 1);
  if (fread(buf, 1, (size_t)n, f) != (size_t)n) {
    fprintf(stderr, "short read on %s\n", path);
    exit(2);
  }
  buf[n] = '\0';
  fclose(f);
  *len = (size_t)n;
  return buf;
}

int main(int argc, char **argv) {
  if (argc != 8) {
    fprintf(stderr,
            "usage: %s sym.json model.params input.f32 n c h w\n",
            argv[0]);
    return 2;
  }
  size_t json_len, param_len, input_len;
  char *json = read_file(argv[1], &json_len);
  char *params = read_file(argv[2], &param_len);
  char *input = read_file(argv[3], &input_len);
  uint32_t shape[4];
  for (int i = 0; i < 4; ++i) shape[i] = (uint32_t)atoi(argv[4 + i]);
  size_t in_size =
      (size_t)shape[0] * shape[1] * shape[2] * shape[3];
  if (input_len != in_size * 4) {
    fprintf(stderr, "input file has %zu bytes, want %zu\n", input_len,
            in_size * 4);
    return 2;
  }

  const char *keys[1] = {"data"};
  uint32_t indptr[2] = {0, 4};
  MXTPUPredictorHandle h;
  if (mxtpu_predict_create(json, params, param_len, 1, keys, indptr,
                           shape, &h) != 0) {
    fprintf(stderr, "create failed: %s\n", mxtpu_predict_last_error());
    return 1;
  }
  if (mxtpu_predict_set_input(h, "data", (const float *)input,
                              in_size) != 0 ||
      mxtpu_predict_forward(h) != 0) {
    fprintf(stderr, "forward failed: %s\n", mxtpu_predict_last_error());
    return 1;
  }
  uint32_t oshape[8], ndim;
  if (mxtpu_predict_get_output_shape(h, 0, oshape, 8, &ndim) != 0) {
    fprintf(stderr, "shape failed: %s\n", mxtpu_predict_last_error());
    return 1;
  }
  size_t osize = 1;
  for (uint32_t i = 0; i < ndim; ++i) osize *= oshape[i];
  float *out = (float *)malloc(osize * 4);
  if (mxtpu_predict_get_output(h, 0, out, osize) != 0) {
    fprintf(stderr, "output failed: %s\n", mxtpu_predict_last_error());
    return 1;
  }
  size_t best = 0;
  for (size_t i = 1; i < osize; ++i)
    if (out[i] > out[best]) best = i;
  printf("TOP1 %zu %.6f\n", best, out[best]);
  printf("LOGITS %.6f %.6f %.6f\n", out[0], osize > 1 ? out[1] : 0.0f,
         osize > 2 ? out[2] : 0.0f);
  mxtpu_predict_free(h);
  free(out);
  free(json);
  free(params);
  free(input);
  return 0;
}
