/* C train/NDArray ABI for mxnet_tpu.
 *
 * Reference parity: the core of include/mxnet/c_api.h (NDArray CRUD,
 * MXImperativeInvoke, symbol load/infer-shape, executor bind/forward/
 * backward) — the subset a cpp-package-style client needs to TRAIN a
 * model, complementing the predict-only surface in mxtpu_predict.h.
 * The implementation (mxtpu_api.cc) drives a forked
 * `python -m mxnet_tpu.api_worker` over pipes; see that module's
 * docstring for the protocol and the worker-process design rationale.
 *
 * All functions return 0 on success, -1 on failure;
 * mxtpu_api_last_error() describes the most recent failure.  Handles
 * are opaque u64 ids scoped to their session.  Tensor payloads are
 * host byte order (little-endian hosts only, like the predict ABI);
 * framing integers are explicitly little-endian.
 */
#ifndef MXTPU_API_H_
#define MXTPU_API_H_

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void *MXTPUSessionHandle;
typedef uint64_t MXTPUHandle; /* ndarray / symbol / executor id */

/* dtype codes for ndarray create/from-data */
#define MXTPU_DTYPE_F32 0
#define MXTPU_DTYPE_I32 1

/* -- session ---------------------------------------------------------- */
int MXTPUSessionCreate(MXTPUSessionHandle *out);
int MXTPUSessionFree(MXTPUSessionHandle sess);
const char *mxtpu_api_last_error(void);

/* -- ndarray ---------------------------------------------------------- */
int MXTPUNDArrayCreate(MXTPUSessionHandle sess, const uint32_t *dims,
                       uint32_t ndim, int dtype, int ones,
                       MXTPUHandle *out);
int MXTPUNDArrayFromData(MXTPUSessionHandle sess, const uint32_t *dims,
                         uint32_t ndim, int dtype, const void *data,
                         size_t nbytes, MXTPUHandle *out);
/* copies the full tensor into buf (caller sizes it from the shape) */
int MXTPUNDArrayToHost(MXTPUSessionHandle sess, MXTPUHandle h, void *buf,
                       size_t nbytes);
/* overwrite an existing array's contents in place (the c_api
 * MXNDArraySyncCopyFromCPU shape); bound executors see the update */
int MXTPUNDArrayCopyFromCPU(MXTPUSessionHandle sess, MXTPUHandle h,
                            const void *data, size_t nbytes);
int MXTPUNDArrayShape(MXTPUSessionHandle sess, MXTPUHandle h,
                      uint32_t *dims, uint32_t cap, uint32_t *ndim);
int MXTPUNDArrayFree(MXTPUSessionHandle sess, MXTPUHandle h);

/* -- imperative invoke ------------------------------------------------ */
/* invoke a registered operator by name with string attributes (the
 * c_api MXImperativeInvoke shape); outputs come back as fresh handles.
 * Ops with in-place semantics (e.g. sgd_update) mutate their input
 * handles, so a bound executor sees the update. */
int MXTPUImperativeInvoke(MXTPUSessionHandle sess, const char *op,
                          uint32_t n_in, const MXTPUHandle *in,
                          uint32_t n_attr, const char *const *keys,
                          const char *const *vals, MXTPUHandle *out,
                          uint32_t out_cap, uint32_t *n_out);

/* -- symbol ----------------------------------------------------------- */
int MXTPUSymbolFromJSON(MXTPUSessionHandle sess, const char *json,
                        MXTPUHandle *out);
int MXTPUSymbolFromFile(MXTPUSessionHandle sess, const char *path,
                        MXTPUHandle *out);
/* newline-joined argument names, NUL-terminated (truncates at cap) */
int MXTPUSymbolListArguments(MXTPUSessionHandle sess, MXTPUHandle sym,
                             char *buf, size_t cap);
/* infer shapes from named input shapes.  Results are flattened
 * (ndims[i] dims each, concatenated) in list_arguments order for args
 * and graph-output order for outputs.  arg_cap/out_cap bound the
 * *entry* counts (sizes of arg_ndims/out_ndims); the *_dims_cap bound
 * the flattened dim buffers. */
int MXTPUSymbolInferShape(MXTPUSessionHandle sess, MXTPUHandle sym,
                          uint32_t n_provided, const char *const *names,
                          const uint32_t *ndims,
                          const uint32_t *dims_concat,
                          uint32_t *arg_ndims, uint32_t arg_cap,
                          uint32_t *arg_dims_concat,
                          uint32_t arg_dims_cap, uint32_t *n_args,
                          uint32_t *out_ndims, uint32_t out_cap,
                          uint32_t *out_dims_concat,
                          uint32_t out_dims_cap, uint32_t *n_outputs);
int MXTPUSymbolFree(MXTPUSessionHandle sess, MXTPUHandle sym);

/* -- executor --------------------------------------------------------- */
/* with_grad != 0 allocates a zeroed gradient array for every bound
 * argument (grad_req "write"); 0 binds for inference. */
int MXTPUExecutorBind(MXTPUSessionHandle sess, MXTPUHandle sym,
                      uint32_t n_args, const char *const *arg_names,
                      const MXTPUHandle *arg_handles, uint32_t n_aux,
                      const char *const *aux_names,
                      const MXTPUHandle *aux_handles, int with_grad,
                      MXTPUHandle *out);
int MXTPUExecutorForward(MXTPUSessionHandle sess, MXTPUHandle exec,
                         int is_train, MXTPUHandle *outputs,
                         uint32_t cap, uint32_t *n_out);
/* n_heads == 0: loss-op semantics (ones_like head gradients) */
int MXTPUExecutorBackward(MXTPUSessionHandle sess, MXTPUHandle exec,
                          uint32_t n_heads, const MXTPUHandle *heads);
/* gradient array for a bound argument; the handle stays valid across
 * backward calls (the executor rebinds it in place) */
int MXTPUExecutorArgGrad(MXTPUSessionHandle sess, MXTPUHandle exec,
                         const char *arg_name, MXTPUHandle *out);
int MXTPUExecutorFree(MXTPUSessionHandle sess, MXTPUHandle exec);

/* -- misc ------------------------------------------------------------- */
int MXTPURandomSeed(MXTPUSessionHandle sess, uint64_t seed);

#ifdef __cplusplus
} /* extern "C" */
#endif

#endif /* MXTPU_API_H_ */
