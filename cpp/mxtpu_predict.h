/* C predict ABI for mxnet_tpu.
 *
 * Reference parity: include/mxnet/c_predict_api.h — the deployment
 * surface that runs an exported model (symbol-json + .params) from C
 * with no Python linkage in the host application.  The implementation
 * (mxtpu_predict.cc) drives a forked mxnet_tpu.predict_worker over a
 * pipe; see that module's docstring for the design rationale.
 *
 * All functions return 0 on success, -1 on failure;
 * mxtpu_predict_last_error() describes the most recent failure.
 *
 * Wire format: integer framing fields (opcodes, lengths, shapes) are
 * explicitly little-endian, so framing errors stay loud everywhere;
 * float tensor payloads are shipped in host byte order, so the ABI as
 * a whole supports little-endian hosts only.
 */
#ifndef MXTPU_PREDICT_H_
#define MXTPU_PREDICT_H_

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void *MXTPUPredictorHandle;

/* Create a predictor.
 *   symbol_json      NUL-terminated symbol json (exported .json file)
 *   param_bytes/len  contents of the exported .params file (reference
 *                    binary format)
 *   num_input_nodes  number of data inputs
 *   input_keys       input names (e.g. {"data"})
 *   input_shape_indptr  CSR-style offsets into input_shape_data, length
 *                    num_input_nodes+1 (reference MXPredCreate layout)
 *   input_shape_data concatenated dims
 */
int mxtpu_predict_create(const char *symbol_json,
                         const void *param_bytes, size_t param_len,
                         uint32_t num_input_nodes,
                         const char **input_keys,
                         const uint32_t *input_shape_indptr,
                         const uint32_t *input_shape_data,
                         MXTPUPredictorHandle *out);

/* Copy a float32 row-major buffer into the named input. */
int mxtpu_predict_set_input(MXTPUPredictorHandle h, const char *key,
                            const float *data, size_t size);

/* Run the forward pass. */
int mxtpu_predict_forward(MXTPUPredictorHandle h);

/* Shape of output `index`: *ndim dims are written to shape_data (caller
 * buffer of capacity cap). */
int mxtpu_predict_get_output_shape(MXTPUPredictorHandle h,
                                   uint32_t index, uint32_t *shape_data,
                                   uint32_t cap, uint32_t *ndim);

/* Copy output `index` (float32, row-major) into data (size floats). */
int mxtpu_predict_get_output(MXTPUPredictorHandle h, uint32_t index,
                             float *data, size_t size);

/* Hot-swap parameters (same layout as create). */
int mxtpu_predict_reload_params(MXTPUPredictorHandle h,
                                const void *param_bytes,
                                size_t param_len);

void mxtpu_predict_free(MXTPUPredictorHandle h);

const char *mxtpu_predict_last_error(void);

#ifdef __cplusplus
}
#endif
#endif /* MXTPU_PREDICT_H_ */
