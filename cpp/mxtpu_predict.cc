// C predict ABI implementation (see mxtpu_predict.h).
//
// Reference parity: src/c_api/c_predict_api.cc.  The predictor is a
// forked `python -m mxnet_tpu.predict_worker` driven over two pipes
// with a length-prefixed binary protocol (documented in that module).
// Rationale for a worker process over embedded CPython: no libpython
// link/version coupling for the host app, crash isolation, and the
// per-call IPC (<1ms) is noise next to the XLA compute it triggers.

#include "mxtpu_predict.h"

#include <errno.h>
#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>

#include <string>
#include <vector>

namespace {

thread_local std::string g_last_error;

struct Predictor {
  pid_t pid = -1;
  int to_worker = -1;    // write end
  int from_worker = -1;  // read end
  std::vector<std::vector<uint32_t>> output_shapes;
};

// A dead worker must surface as EPIPE/-1, not kill the host app with
// SIGPIPE: block the signal on this thread for the write's duration
// and consume any pending instance.
class ScopedSigpipeBlock {
 public:
  ScopedSigpipeBlock() {
    sigemptyset(&set_);
    sigaddset(&set_, SIGPIPE);
    blocked_ = pthread_sigmask(SIG_BLOCK, &set_, &old_) == 0;
  }
  ~ScopedSigpipeBlock() {
    if (!blocked_) return;
    struct timespec zero = {0, 0};
    while (sigtimedwait(&set_, nullptr, &zero) > 0) {
    }
    pthread_sigmask(SIG_SETMASK, &old_, nullptr);
  }

 private:
  sigset_t set_, old_;
  bool blocked_ = false;
};

bool write_all(int fd, const void *buf, size_t n) {
  ScopedSigpipeBlock guard;
  const char *p = static_cast<const char *>(buf);
  while (n) {
    ssize_t w = write(fd, p, n);
    if (w < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

bool read_all(int fd, void *buf, size_t n) {
  char *p = static_cast<char *>(buf);
  while (n) {
    ssize_t r = read(fd, p, n);
    if (r < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    if (r == 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

// request = u8 opcode | u64 len | payload ; response = u8 status | u64
// len | payload.  Returns false (with g_last_error set) on transport or
// worker-reported error.
bool roundtrip(Predictor *p, uint8_t opcode, const std::string &payload,
               std::string *reply) {
  // lengths travel little-endian on the wire (the python worker parses
  // '<Q'); serialize explicitly so a big-endian host still speaks the
  // documented protocol rather than its native byte order
  char head[9];
  head[0] = static_cast<char>(opcode);
  uint64_t len = payload.size();
  for (int i = 0; i < 8; ++i)
    head[1 + i] = static_cast<char>((len >> (8 * i)) & 0xff);
  if (!write_all(p->to_worker, head, 9) ||
      (!payload.empty() &&
       !write_all(p->to_worker, payload.data(), payload.size()))) {
    g_last_error = "predict worker pipe write failed";
    return false;
  }
  char rhead[9];
  if (!read_all(p->from_worker, rhead, 9)) {
    g_last_error = "predict worker died (pipe closed)";
    return false;
  }
  uint8_t status = static_cast<uint8_t>(rhead[0]);
  uint64_t rlen = 0;
  for (int i = 0; i < 8; ++i)
    rlen |= static_cast<uint64_t>(static_cast<uint8_t>(rhead[1 + i]))
            << (8 * i);
  if (rlen > (1ull << 33)) {  // corrupted frame, not a real reply
    g_last_error = "predict worker protocol corrupt (reply length)";
    return false;
  }
  std::string body(rlen, '\0');
  if (rlen && !read_all(p->from_worker, &body[0], rlen)) {
    g_last_error = "predict worker reply truncated";
    return false;
  }
  if (status != 0) {
    g_last_error = "predict worker error: " + body;
    return false;
  }
  if (reply) *reply = std::move(body);
  return true;
}

// integer framing fields travel little-endian ('<I'/'<Q' on the worker
// side); serialize explicitly so the framing survives a big-endian
// host.  NOTE: float tensor payloads are still shipped raw (host byte
// order) — the full ABI remains little-endian-host-only, the explicit
// framing just keeps the failure mode loud instead of corrupting the
// protocol stream.
void append_u32(std::string *s, uint32_t v) {
  char b[4];
  for (int i = 0; i < 4; ++i)
    b[i] = static_cast<char>((v >> (8 * i)) & 0xff);
  s->append(b, 4);
}
void append_u64(std::string *s, uint64_t v) {
  char b[8];
  for (int i = 0; i < 8; ++i)
    b[i] = static_cast<char>((v >> (8 * i)) & 0xff);
  s->append(b, 8);
}
uint32_t parse_u32(const char *p) {
  uint32_t v = 0;
  for (int i = 0; i < 4; ++i)
    v |= static_cast<uint32_t>(static_cast<uint8_t>(p[i])) << (8 * i);
  return v;
}

bool spawn_worker(Predictor *p) {
  int in_pipe[2], out_pipe[2];
  if (pipe(in_pipe) != 0) {
    g_last_error = "pipe() failed";
    return false;
  }
  if (pipe(out_pipe) != 0) {
    g_last_error = "pipe() failed";
    close(in_pipe[0]);
    close(in_pipe[1]);
    return false;
  }
  pid_t pid = fork();
  if (pid < 0) {
    g_last_error = "fork() failed";
    close(in_pipe[0]);
    close(in_pipe[1]);
    close(out_pipe[0]);
    close(out_pipe[1]);
    return false;
  }
  if (pid == 0) {  // child: stdin <- in_pipe, stdout -> out_pipe
    dup2(in_pipe[0], 0);
    dup2(out_pipe[1], 1);
    close(in_pipe[0]);
    close(in_pipe[1]);
    close(out_pipe[0]);
    close(out_pipe[1]);
    const char *py = getenv("MXTPU_PYTHON");
    if (!py) py = "python3";
    execlp(py, py, "-m", "mxnet_tpu.predict_worker",
           static_cast<char *>(nullptr));
    perror("execlp mxnet_tpu.predict_worker");
    _exit(127);
  }
  close(in_pipe[0]);
  close(out_pipe[1]);
  p->pid = pid;
  p->to_worker = in_pipe[1];
  p->from_worker = out_pipe[0];
  return true;
}

}  // namespace

extern "C" {

const char *mxtpu_predict_last_error(void) { return g_last_error.c_str(); }

int mxtpu_predict_create(const char *symbol_json, const void *param_bytes,
                         size_t param_len, uint32_t num_input_nodes,
                         const char **input_keys,
                         const uint32_t *input_shape_indptr,
                         const uint32_t *input_shape_data,
                         MXTPUPredictorHandle *out) {
  Predictor *p = new Predictor();
  if (!spawn_worker(p)) {
    delete p;
    return -1;
  }
  std::string payload;
  uint64_t jlen = strlen(symbol_json);
  append_u64(&payload, jlen);
  payload.append(symbol_json, jlen);
  append_u64(&payload, param_len);
  payload.append(static_cast<const char *>(param_bytes), param_len);
  append_u32(&payload, num_input_nodes);
  for (uint32_t i = 0; i < num_input_nodes; ++i) {
    uint32_t nlen = static_cast<uint32_t>(strlen(input_keys[i]));
    append_u32(&payload, nlen);
    payload.append(input_keys[i], nlen);
    uint32_t ndim = input_shape_indptr[i + 1] - input_shape_indptr[i];
    append_u32(&payload, ndim);
    for (uint32_t d = 0; d < ndim; ++d)
      append_u32(&payload, input_shape_data[input_shape_indptr[i] + d]);
  }
  std::string reply;
  if (!roundtrip(p, 1, payload, &reply)) {
    mxtpu_predict_free(p);
    return -1;
  }
  // bounds-checked parse: a corrupted reply must fail, not overread
  size_t off = 0;
  auto take_u32 = [&](uint32_t *v) {
    if (off + 4 > reply.size()) return false;
    *v = parse_u32(reply.data() + off);
    off += 4;
    return true;
  };
  uint32_t n_out = 0;
  bool parse_ok = take_u32(&n_out) && n_out <= 4096;
  if (parse_ok) {
    p->output_shapes.resize(n_out);
    for (uint32_t i = 0; parse_ok && i < n_out; ++i) {
      uint32_t ndim = 0;
      parse_ok = take_u32(&ndim) && ndim <= 64 &&
                 off + 4ull * ndim <= reply.size();
      if (parse_ok) {
        p->output_shapes[i].resize(ndim);
        for (uint32_t d = 0; d < ndim; ++d)
          p->output_shapes[i][d] = parse_u32(reply.data() + off + 4ull * d);
        off += 4ull * ndim;
      }
    }
  }
  if (!parse_ok) {
    g_last_error = "predict worker protocol corrupt (create reply)";
    mxtpu_predict_free(p);
    return -1;
  }
  *out = p;
  return 0;
}

int mxtpu_predict_set_input(MXTPUPredictorHandle h, const char *key,
                            const float *data, size_t size) {
  Predictor *p = static_cast<Predictor *>(h);
  std::string payload;
  uint32_t nlen = static_cast<uint32_t>(strlen(key));
  append_u32(&payload, nlen);
  payload.append(key, nlen);
  payload.append(reinterpret_cast<const char *>(data), size * 4);
  return roundtrip(p, 2, payload, nullptr) ? 0 : -1;
}

int mxtpu_predict_forward(MXTPUPredictorHandle h) {
  return roundtrip(static_cast<Predictor *>(h), 3, "", nullptr) ? 0 : -1;
}

int mxtpu_predict_get_output_shape(MXTPUPredictorHandle h, uint32_t index,
                                   uint32_t *shape_data, uint32_t cap,
                                   uint32_t *ndim) {
  Predictor *p = static_cast<Predictor *>(h);
  if (index >= p->output_shapes.size()) {
    g_last_error = "output index out of range";
    return -1;
  }
  const auto &s = p->output_shapes[index];
  *ndim = static_cast<uint32_t>(s.size());
  if (cap < s.size()) {
    g_last_error = "shape buffer too small";
    return -1;
  }
  memcpy(shape_data, s.data(), 4 * s.size());
  return 0;
}

int mxtpu_predict_get_output(MXTPUPredictorHandle h, uint32_t index,
                             float *data, size_t size) {
  Predictor *p = static_cast<Predictor *>(h);
  std::string payload, reply;
  append_u32(&payload, index);
  if (!roundtrip(p, 4, payload, &reply)) return -1;
  if (reply.size() != size * 4) {
    g_last_error = "output size mismatch: worker sent " +
                   std::to_string(reply.size() / 4) + " floats";
    return -1;
  }
  memcpy(data, reply.data(), reply.size());
  return 0;
}

int mxtpu_predict_reload_params(MXTPUPredictorHandle h,
                                const void *param_bytes, size_t param_len) {
  Predictor *p = static_cast<Predictor *>(h);
  std::string payload;
  append_u64(&payload, param_len);
  payload.append(static_cast<const char *>(param_bytes), param_len);
  return roundtrip(p, 5, payload, nullptr) ? 0 : -1;
}

void mxtpu_predict_free(MXTPUPredictorHandle h) {
  Predictor *p = static_cast<Predictor *>(h);
  if (!p) return;
  if (p->to_worker >= 0) {
    char head[9] = {0};  // opcode 0 = CLOSE, len 0
    write_all(p->to_worker, head, 9);
    close(p->to_worker);
  }
  if (p->from_worker >= 0) close(p->from_worker);
  if (p->pid > 0) {
    int status;
    waitpid(p->pid, &status, 0);
  }
  delete p;
}

}  // extern "C"
