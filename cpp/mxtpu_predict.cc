// C predict ABI implementation (see mxtpu_predict.h).
//
// Reference parity: src/c_api/c_predict_api.cc.  The predictor is a
// forked `python -m mxnet_tpu.predict_worker` driven over two pipes
// with a length-prefixed binary protocol (documented in that module).
// Rationale for a worker process over embedded CPython: no libpython
// link/version coupling for the host app, crash isolation, and the
// per-call IPC (<1ms) is noise next to the XLA compute it triggers.

#include "mxtpu_predict.h"

#include <errno.h>
#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>

#include <string>
#include <vector>

#include "mxtpu_ipc.h"

namespace {

using mxtpu_ipc::append_u32;
using mxtpu_ipc::append_u64;
using mxtpu_ipc::parse_u32;

thread_local std::string g_last_error;

struct Predictor {
  mxtpu_ipc::Worker w;
  std::vector<std::vector<uint32_t>> output_shapes;
};

bool spawn_worker(Predictor *p) {
  return mxtpu_ipc::spawn_worker("mxnet_tpu.predict_worker", &p->w,
                                 &g_last_error);
}

bool roundtrip(Predictor *p, uint8_t opcode, const std::string &payload,
               std::string *reply) {
  return mxtpu_ipc::roundtrip(p->w, opcode, payload, reply,
                              &g_last_error, "predict");
}

}  // namespace

extern "C" {

const char *mxtpu_predict_last_error(void) { return g_last_error.c_str(); }

int mxtpu_predict_create(const char *symbol_json, const void *param_bytes,
                         size_t param_len, uint32_t num_input_nodes,
                         const char **input_keys,
                         const uint32_t *input_shape_indptr,
                         const uint32_t *input_shape_data,
                         MXTPUPredictorHandle *out) {
  Predictor *p = new Predictor();
  if (!spawn_worker(p)) {
    delete p;
    return -1;
  }
  std::string payload;
  uint64_t jlen = strlen(symbol_json);
  append_u64(&payload, jlen);
  payload.append(symbol_json, jlen);
  append_u64(&payload, param_len);
  payload.append(static_cast<const char *>(param_bytes), param_len);
  append_u32(&payload, num_input_nodes);
  for (uint32_t i = 0; i < num_input_nodes; ++i) {
    uint32_t nlen = static_cast<uint32_t>(strlen(input_keys[i]));
    append_u32(&payload, nlen);
    payload.append(input_keys[i], nlen);
    uint32_t ndim = input_shape_indptr[i + 1] - input_shape_indptr[i];
    append_u32(&payload, ndim);
    for (uint32_t d = 0; d < ndim; ++d)
      append_u32(&payload, input_shape_data[input_shape_indptr[i] + d]);
  }
  std::string reply;
  if (!roundtrip(p, 1, payload, &reply)) {
    mxtpu_predict_free(p);
    return -1;
  }
  // bounds-checked parse: a corrupted reply must fail, not overread
  size_t off = 0;
  auto take_u32 = [&](uint32_t *v) {
    if (off + 4 > reply.size()) return false;
    *v = parse_u32(reply.data() + off);
    off += 4;
    return true;
  };
  uint32_t n_out = 0;
  bool parse_ok = take_u32(&n_out) && n_out <= 4096;
  if (parse_ok) {
    p->output_shapes.resize(n_out);
    for (uint32_t i = 0; parse_ok && i < n_out; ++i) {
      uint32_t ndim = 0;
      parse_ok = take_u32(&ndim) && ndim <= 64 &&
                 off + 4ull * ndim <= reply.size();
      if (parse_ok) {
        p->output_shapes[i].resize(ndim);
        for (uint32_t d = 0; d < ndim; ++d)
          p->output_shapes[i][d] = parse_u32(reply.data() + off + 4ull * d);
        off += 4ull * ndim;
      }
    }
  }
  if (!parse_ok) {
    g_last_error = "predict worker protocol corrupt (create reply)";
    mxtpu_predict_free(p);
    return -1;
  }
  *out = p;
  return 0;
}

int mxtpu_predict_set_input(MXTPUPredictorHandle h, const char *key,
                            const float *data, size_t size) {
  Predictor *p = static_cast<Predictor *>(h);
  std::string payload;
  uint32_t nlen = static_cast<uint32_t>(strlen(key));
  append_u32(&payload, nlen);
  payload.append(key, nlen);
  payload.append(reinterpret_cast<const char *>(data), size * 4);
  return roundtrip(p, 2, payload, nullptr) ? 0 : -1;
}

int mxtpu_predict_forward(MXTPUPredictorHandle h) {
  return roundtrip(static_cast<Predictor *>(h), 3, "", nullptr) ? 0 : -1;
}

int mxtpu_predict_get_output_shape(MXTPUPredictorHandle h, uint32_t index,
                                   uint32_t *shape_data, uint32_t cap,
                                   uint32_t *ndim) {
  Predictor *p = static_cast<Predictor *>(h);
  if (index >= p->output_shapes.size()) {
    g_last_error = "output index out of range";
    return -1;
  }
  const auto &s = p->output_shapes[index];
  *ndim = static_cast<uint32_t>(s.size());
  if (cap < s.size()) {
    g_last_error = "shape buffer too small";
    return -1;
  }
  memcpy(shape_data, s.data(), 4 * s.size());
  return 0;
}

int mxtpu_predict_get_output(MXTPUPredictorHandle h, uint32_t index,
                             float *data, size_t size) {
  Predictor *p = static_cast<Predictor *>(h);
  std::string payload, reply;
  append_u32(&payload, index);
  if (!roundtrip(p, 4, payload, &reply)) return -1;
  if (reply.size() != size * 4) {
    g_last_error = "output size mismatch: worker sent " +
                   std::to_string(reply.size() / 4) + " floats";
    return -1;
  }
  memcpy(data, reply.data(), reply.size());
  return 0;
}

int mxtpu_predict_reload_params(MXTPUPredictorHandle h,
                                const void *param_bytes, size_t param_len) {
  Predictor *p = static_cast<Predictor *>(h);
  std::string payload;
  append_u64(&payload, param_len);
  payload.append(static_cast<const char *>(param_bytes), param_len);
  return roundtrip(p, 5, payload, nullptr) ? 0 : -1;
}

void mxtpu_predict_free(MXTPUPredictorHandle h) {
  Predictor *p = static_cast<Predictor *>(h);
  if (!p) return;
  mxtpu_ipc::shutdown_worker(&p->w);
  delete p;
}

}  // extern "C"
