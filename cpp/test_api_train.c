/* C training client for the train/NDArray ABI (mxtpu_api.h) —
 * reference parity: a cpp-package-style client (cpp-package/example/
 * mlp.cpp shape) driving a full train loop from plain C: symbol load,
 * infer-shape, executor bind with gradients, forward/backward, and
 * in-place sgd_update via imperative invoke.
 *
 * Usage: test_api_train <mlp_symbol.json>
 * Trains y = relu(x W1 + b1) W2 + b2 against a linear target with MSE
 * (LinearRegressionOutput) on synthetic data; prints per-epoch loss and
 * "TRAIN OK first=<loss0> last=<lossN>"; exits nonzero unless the loss
 * fell by 10x.
 */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "mxtpu_api.h"

#define BATCH 32
#define DIN 8
#define DH 16
#define DOUT 1
#define STEPS 150

static unsigned long rng_state = 12345;
static float frand(void) { /* deterministic LCG in [-0.5, 0.5) */
  rng_state = rng_state * 6364136223846793005UL + 1442695040888963407UL;
  return ((rng_state >> 33) & 0xffffff) / (float)0x1000000 - 0.5f;
}

static void die(const char *what) {
  fprintf(stderr, "FAIL %s: %s\n", what, mxtpu_api_last_error());
  exit(1);
}

int main(int argc, char **argv) {
  if (argc != 2) {
    fprintf(stderr, "usage: %s mlp_symbol.json\n", argv[0]);
    return 2;
  }

  MXTPUSessionHandle sess;
  if (MXTPUSessionCreate(&sess) != 0) die("session");
  if (MXTPURandomSeed(sess, 7) != 0) die("seed");

  MXTPUHandle sym;
  if (MXTPUSymbolFromFile(sess, argv[1], &sym) != 0) die("symbol load");

  char args_buf[1024];
  if (MXTPUSymbolListArguments(sess, sym, args_buf, sizeof(args_buf)))
    die("list args");
  printf("ARGS %s\n", args_buf);

  /* infer shapes from the data input alone */
  const char *in_names[] = {"data", "label"};
  uint32_t in_ndims[] = {2, 2};
  uint32_t in_dims[] = {BATCH, DIN, BATCH, DOUT};
  uint32_t arg_ndims[16], arg_dims[64], n_args = 0;
  uint32_t out_ndims[4], out_dims[16], n_outs = 0;
  if (MXTPUSymbolInferShape(sess, sym, 2, in_names, in_ndims, in_dims,
                            arg_ndims, 16, arg_dims, 64, &n_args,
                            out_ndims, 4, out_dims, 16, &n_outs) != 0)
    die("infer shape");
  printf("INFER n_args=%u n_outs=%u\n", n_args, n_outs);

  /* synthetic regression task: y = sum(x) * 0.5 */
  float xbuf[BATCH * DIN], ybuf[BATCH * DOUT];

  /* parameters: small random init on the host */
  float w1[DH * DIN], b1[DH], w2[DOUT * DH], b2[DOUT];
  for (int i = 0; i < DH * DIN; ++i) w1[i] = frand() * 0.6f;
  for (int i = 0; i < DH; ++i) b1[i] = 0.0f;
  for (int i = 0; i < DOUT * DH; ++i) w2[i] = frand() * 0.6f;
  for (int i = 0; i < DOUT; ++i) b2[i] = 0.0f;

  uint32_t d_x[] = {BATCH, DIN}, d_y[] = {BATCH, DOUT};
  uint32_t d_w1[] = {DH, DIN}, d_b1[] = {DH};
  uint32_t d_w2[] = {DOUT, DH}, d_b2[] = {DOUT};
  MXTPUHandle h_x, h_y, h_w1, h_b1, h_w2, h_b2;
  if (MXTPUNDArrayCreate(sess, d_x, 2, MXTPU_DTYPE_F32, 0, &h_x) ||
      MXTPUNDArrayCreate(sess, d_y, 2, MXTPU_DTYPE_F32, 0, &h_y) ||
      MXTPUNDArrayFromData(sess, d_w1, 2, MXTPU_DTYPE_F32, w1,
                           sizeof(w1), &h_w1) ||
      MXTPUNDArrayFromData(sess, d_b1, 1, MXTPU_DTYPE_F32, b1,
                           sizeof(b1), &h_b1) ||
      MXTPUNDArrayFromData(sess, d_w2, 2, MXTPU_DTYPE_F32, w2,
                           sizeof(w2), &h_w2) ||
      MXTPUNDArrayFromData(sess, d_b2, 1, MXTPU_DTYPE_F32, b2,
                           sizeof(b2), &h_b2))
    die("ndarray create");

  /* sanity: shape round-trip */
  uint32_t shp[4], nd = 0;
  if (MXTPUNDArrayShape(sess, h_w1, shp, 4, &nd) != 0 || nd != 2 ||
      shp[0] != DH || shp[1] != DIN)
    die("shape check");

  const char *names[] = {"data", "fc1_weight", "fc1_bias", "fc2_weight",
                         "fc2_bias", "label"};
  MXTPUHandle handles[] = {h_x, h_w1, h_b1, h_w2, h_b2, h_y};
  MXTPUHandle exe;
  if (MXTPUExecutorBind(sess, sym, 6, names, handles, 0, NULL, NULL, 1,
                        &exe) != 0)
    die("bind");

  MXTPUHandle g_w1, g_b1, g_w2, g_b2;
  if (MXTPUExecutorArgGrad(sess, exe, "fc1_weight", &g_w1) ||
      MXTPUExecutorArgGrad(sess, exe, "fc1_bias", &g_b1) ||
      MXTPUExecutorArgGrad(sess, exe, "fc2_weight", &g_w2) ||
      MXTPUExecutorArgGrad(sess, exe, "fc2_bias", &g_b2))
    die("arg grad");

  /* rescale_grad = 1/batch: regression-output grads are summed over
   * the batch (the reference Trainer discipline) */
  const char *kw[] = {"lr", "rescale_grad"};
  const char *kv[] = {"0.5", "0.03125"};
  MXTPUHandle weights[] = {h_w1, h_b1, h_w2, h_b2};
  MXTPUHandle grads[] = {g_w1, g_b1, g_w2, g_b2};

  float first_loss = -1.0f, loss = 0.0f;
  for (int step = 0; step < STEPS; ++step) {
    /* fresh synthetic batch, uploaded into new arrays bound by name */
    for (int i = 0; i < BATCH; ++i) {
      float s = 0.0f;
      for (int j = 0; j < DIN; ++j) {
        xbuf[i * DIN + j] = frand();
        s += xbuf[i * DIN + j];
      }
      ybuf[i] = 0.5f * s;
    }
    /* refresh the bound data/label arrays in place (the c_api
     * MXNDArraySyncCopyFromCPU discipline — the executor sees the
     * update without rebinding) */
    if (MXTPUNDArrayCopyFromCPU(sess, h_x, xbuf, sizeof(xbuf)) ||
        MXTPUNDArrayCopyFromCPU(sess, h_y, ybuf, sizeof(ybuf)))
      die("batch upload");

    MXTPUHandle outs[4];
    uint32_t n_out = 0;
    if (MXTPUExecutorForward(sess, exe, 1, outs, 4, &n_out) != 0)
      die("forward");
    if (MXTPUExecutorBackward(sess, exe, 0, NULL) != 0) die("backward");

    /* read the prediction to compute MSE host-side */
    float pred[BATCH * DOUT];
    if (MXTPUNDArrayToHost(sess, outs[0], pred, sizeof(pred)) != 0)
      die("fetch pred");
    loss = 0.0f;
    for (int i = 0; i < BATCH; ++i) {
      float d2 = pred[i] - ybuf[i];
      loss += d2 * d2;
    }
    loss /= BATCH;
    if (first_loss < 0) first_loss = loss;
    if (step % 10 == 0) printf("STEP %d mse=%.6f\n", step, loss);
    for (uint32_t i = 0; i < n_out; ++i) MXTPUNDArrayFree(sess, outs[i]);

    /* in-place SGD on each weight through imperative invoke */
    for (int i = 0; i < 4; ++i) {
      MXTPUHandle upd_in[] = {weights[i], grads[i]};
      MXTPUHandle upd_out[1];
      uint32_t n_upd = 0;
      if (MXTPUImperativeInvoke(sess, "sgd_update", 2, upd_in, 2, kw,
                                kv, upd_out, 1, &n_upd) != 0)
        die("sgd_update");
    }
  }

  printf("TRAIN OK first=%.6f last=%.6f\n", first_loss, loss);
  MXTPUExecutorFree(sess, exe);
  MXTPUSymbolFree(sess, sym);
  MXTPUSessionFree(sess);
  return loss < first_loss / 10.0f ? 0 : 1;
}
