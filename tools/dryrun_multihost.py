"""Two-process multi-host dryrun (VERDICT r3 next-round #8).

Validates BOTH distributed paths over a DCN-style 2-host topology
without real multi-host hardware:

1. **Collective path** — 2 OS processes x 4 virtual CPU devices joined
   via ``jax.distributed`` (the ``parallel.mesh.init_distributed``
   bootstrap), one ``ShardedTrainer`` training step jitted over the
   global 8-device ``dp(hosts) x tp(local)`` mesh.  Each process feeds
   its own local batch shard (``make_array_from_process_local_data``),
   mirroring the reference's per-worker data loading; gradients cross
   the process boundary through compiler-inserted collectives — the
   DCN analogue of SURVEY §2.3's multi-machine dist_sync.
2. **Parameter-server path** — 1 server process + 2 worker processes
   over kvstore ``dist_sync`` (``kvstore_server.py``), one
   init/push/pull round verifying cross-worker aggregation.

Writes a MULTICHIP-style artifact:
    python tools/dryrun_multihost.py --json MULTIHOST_r04.json

Also hosts the offline sharded-checkpoint validator (no mesh, no jax —
pure file inspection; nonzero exit on coverage gaps / torn shards):
    python tools/dryrun_multihost.py --check-manifest /ckpt/dir [--step N]
"""
import argparse
import json
import os
import socket
import subprocess
import sys
import time

HERE = os.path.abspath(__file__)
REPO = os.path.dirname(os.path.dirname(HERE))
sys.path.insert(0, REPO)


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ---------------------------------------------------------------------------
# worker body (runs in a fresh subprocess with JAX_PLATFORMS=cpu)
# ---------------------------------------------------------------------------


def collective_worker(rank, n_procs, dev_per_proc, port):
    import jax

    jax.config.update("jax_platforms", "cpu")
    os.environ["MXNET_DIST_COORDINATOR"] = "127.0.0.1:%d" % port
    os.environ["MXNET_DIST_NUM_PROCS"] = str(n_procs)
    os.environ["MXNET_DIST_PROC_ID"] = str(rank)

    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import nd, gluon, parallel
    from mxnet_tpu.gluon import nn
    from jax.sharding import PartitionSpec as P

    try:
        # env-driven bootstrap (retry-with-backoff inside); raises the
        # typed DistributedUnavailable on an unreachable coordinator
        up = parallel.bootstrap_distributed()
    except parallel.DistributedUnavailable as e:
        raise AssertionError("jax.distributed bootstrap failed: %s" % e)
    assert up, "jax.distributed bootstrap failed: not configured"
    assert jax.process_count() == n_procs
    devs = jax.devices()
    assert len(devs) == n_procs * dev_per_proc, \
        "global mesh sees %d devices" % len(devs)

    # default: dp spans the hosts (DCN), tp the intra-host devices
    # (ICI); --mesh overrides via the env relay (validated upstream)
    axes = parallel.parse_mesh(os.environ.get("MXTPU_MESH_SPEC")) or \
        {"dp": n_procs, "tp": dev_per_proc}
    mesh = parallel.make_mesh(axes, devs)
    local = [d for d in mesh.devices.flat if d.process_index == rank]
    print("MULTIHOST_MESH rank=%d axes=%s local_devices=%d" % (
        rank, json.dumps(parallel.mesh_shape(mesh), sort_keys=True),
        len(local)), flush=True)

    mx.random.seed(7)      # identical replicated params on every host
    np.random.seed(7)
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"), nn.Dense(8))
    net.initialize()

    tp_size = axes.get("tp", 0)

    def spec_fn(name, shape):
        if tp_size and name.endswith("weight") and len(shape) == 2 \
                and shape[0] % tp_size == 0:
            return P("tp", None)
        return None

    loss_fn = gluon.loss.L2Loss()
    trainer = parallel.ShardedTrainer(
        net, lambda o, l: loss_fn(o, l), mesh=mesh, optimizer="sgd",
        optimizer_params={"learning_rate": 0.1}, param_spec_fn=spec_fn)

    # per-worker local batch shard (rank-dependent data, reference
    # per-worker iterator semantics)
    rng = np.random.RandomState(100 + rank)
    X = rng.rand(8, 16).astype(np.float32)
    Y = rng.rand(8, 8).astype(np.float32)
    xs, ys = trainer.shard_batch(nd.array(X), nd.array(Y))
    losses = []
    for _ in range(2):
        loss = trainer.step([xs], ys)
        jax.block_until_ready(loss)
        losses.append(float(np.asarray(loss)))
    assert all(np.isfinite(v) for v in losses), losses
    assert losses[1] < losses[0], "no training progress: %s" % losses
    # collective gather-back: tp-sharded params re-replicate across the
    # process boundary before the host fetch
    trainer.sync_to_net()
    for p in net.collect_params().values():
        assert np.isfinite(p.data().asnumpy()).all(), p.name
    print("MULTIHOST_LOSS rank=%d %r" % (rank, losses), flush=True)


def ps_server(port, n_workers):
    os.environ.update({
        "DMLC_ROLE": "server", "DMLC_PS_ROOT_URI": "127.0.0.1",
        "DMLC_PS_ROOT_PORT": str(port), "DMLC_NUM_WORKER": str(n_workers),
        "MXNET_PLATFORM": "cpu",
    })
    from mxnet_tpu.kvstore_server import run_server

    run_server()


def ps_worker(rank, port, n_workers):
    os.environ.update({
        "DMLC_ROLE": "worker", "DMLC_RANK": str(rank),
        "DMLC_PS_ROOT_URI": "127.0.0.1", "DMLC_PS_ROOT_PORT": str(port),
        "DMLC_NUM_WORKER": str(n_workers), "MXNET_PLATFORM": "cpu",
    })
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import nd

    kv = mx.kv.create("dist_sync")
    kv.init(3, nd.array(np.zeros((4, 4), np.float32)))
    kv.push(3, [nd.array(np.full((4, 4), float(rank + 1), np.float32))])
    out = nd.array(np.zeros((4, 4), np.float32))
    kv.pull(3, out=[out])
    total = float(out.asnumpy()[0, 0])
    expect = float(sum(range(1, n_workers + 1)))
    assert total == expect, (total, expect)
    print("MULTIHOST_PS rank=%d sum=%.1f" % (rank, total), flush=True)


# ---------------------------------------------------------------------------
# orchestrator
# ---------------------------------------------------------------------------


# mirror of parallel.mesh.MESH_AXES — local copy keeps the orchestrator
# free of the jax import (workers validate again through parse_mesh)
_MESH_AXES = ("dp", "fsdp", "pp", "ep", "sp", "mp", "tp")


def _parse_mesh_arg(spec):
    """Lightweight 'dp=2,tp=4' parse for the orchestrator (no jax)."""
    axes = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, size = part.partition("=")
        name = name.strip()
        if name not in _MESH_AXES or not size.strip().isdigit():
            raise SystemExit("bad --mesh entry %r (axis=size over %s)"
                             % (part, list(_MESH_AXES)))
        axes[name] = int(size)
    return axes


def _print_host_layout(axes, n_procs, dev_per_proc):
    """The resolved per-host view of --mesh: which axes span hosts (DCN)
    vs stay intra-host (ICI), and each rank's global device slice."""
    total = 1
    for v in axes.values():
        total *= v
    if total != n_procs * dev_per_proc:
        raise SystemExit(
            "--mesh %s needs %d devices; topology has %d procs x %d = %d"
            % (axes, total, n_procs, dev_per_proc,
               n_procs * dev_per_proc))
    order = [a for a in _MESH_AXES if a in axes]
    # device ids are laid out row-major in canonical axis order, hosts
    # own contiguous dev_per_proc blocks: an axis group touches ids
    # {i, i+stride, ..., i+(size-1)*stride}, so it stays inside one
    # host block only when its whole extent (stride * size) fits the
    # block — e.g. dp=4,tp=2 over 2x4 hosts has dp stride 2 but group
    # {0,2,4,6}, which crosses the host boundary
    stride = total
    spans = []
    for a in order:
        size = axes[a]
        extent = stride          # = stride(after) * size
        stride //= size
        spans.append((a, size, "hosts/DCN" if extent > dev_per_proc
                      and size > 1 else "local/ICI"))
    print("mesh %s over %d hosts x %d devices:"
          % (",".join("%s=%d" % (a, axes[a]) for a in order), n_procs,
             dev_per_proc), flush=True)
    for a, size, where in spans:
        print("  axis %-4s size %d  (%s)" % (a, size, where), flush=True)
    for r in range(n_procs):
        print("  rank %d: global devices [%d..%d]"
              % (r, r * dev_per_proc, (r + 1) * dev_per_proc - 1),
              flush=True)


def check_manifest(directory, step=None, prefix="ckpt"):
    """Offline sharded-checkpoint validation (manifest schema, shard
    presence/size, per-chunk digests, exact global coverage).  Returns
    a process exit code: 0 = restorable on any topology."""
    from mxnet_tpu.checkpoint import validate_sharded_checkpoint

    step, problems = validate_sharded_checkpoint(directory, step=step,
                                                 prefix=prefix)
    if step is None:
        print("check-manifest: %s" % problems[0], flush=True)
        return 2
    if problems:
        print("check-manifest: step %d has %d problem(s):"
              % (step, len(problems)), flush=True)
        for pr in problems:
            print("  - %s" % pr, flush=True)
        return 1
    print("check-manifest: step %d OK (restorable on any topology)"
          % step, flush=True)
    return 0


def run(n_procs=2, dev_per_proc=4, json_path=None, mesh=None):
    result = {"n_procs": n_procs, "dev_per_proc": dev_per_proc,
              "topology": "dp(%d hosts over DCN) x tp(%d local devices)"
                          % (n_procs, dev_per_proc)}
    if mesh:
        axes = _parse_mesh_arg(mesh)
        _print_host_layout(axes, n_procs, dev_per_proc)
        result["mesh"] = axes
        result["topology"] = mesh
        os.environ["MXTPU_MESH_SPEC"] = mesh  # relay to workers

    # --- 1. jax.distributed collective step ---
    port = _free_port()
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if not f.startswith("--xla_force_host_platform_device_count")]
    flags.append("--xla_force_host_platform_device_count=%d"
                 % dev_per_proc)
    env["XLA_FLAGS"] = " ".join(flags)
    procs = [subprocess.Popen(
        [sys.executable, HERE, "--collective-worker", str(r),
         str(n_procs), str(dev_per_proc), str(port)],
        env=env, cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True) for r in range(n_procs)]
    outs = []
    ok = True
    for p in procs:
        try:
            out, _ = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            p.kill()
            out = "TIMEOUT"
        outs.append(out)
        ok = ok and p.returncode == 0
    result["collective_ok"] = ok
    losses = [ln for o in outs for ln in o.splitlines()
              if ln.startswith("MULTIHOST_LOSS")]
    result["collective_losses"] = losses
    if not ok:
        # raw worker output: callers (tests/test_multihost.py) classify
        # environmental bootstrap/timeout failures vs real regressions
        result["collective_outs"] = outs
    print("\n".join(losses) if ok else "COLLECTIVE FAILED:\n%s"
          % "\n".join(outs), flush=True)

    # --- 2. parameter-server dist_sync round ---
    port = _free_port()
    env_ps = dict(os.environ, MXNET_PLATFORM="cpu", JAX_PLATFORMS="cpu")
    sp = subprocess.Popen(
        [sys.executable, HERE, "--ps-server", str(port), str(n_procs)],
        env=env_ps, cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    time.sleep(1.0)
    workers = [subprocess.Popen(
        [sys.executable, HERE, "--ps-worker", str(r), str(port),
         str(n_procs)],
        env=env_ps, cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True) for r in range(n_procs)]
    ps_ok = True
    ps_out = []
    for p in workers:
        try:
            out, _ = p.communicate(timeout=120)
        except subprocess.TimeoutExpired:
            p.kill()
            out = "TIMEOUT"
        ps_out.append(out)
        ps_ok = ps_ok and p.returncode == 0
    sp.kill()
    result["ps_ok"] = ps_ok
    result["ps_lines"] = [ln for o in ps_out for ln in o.splitlines()
                          if ln.startswith("MULTIHOST_PS")]
    print("\n".join(result["ps_lines"]) if ps_ok else "PS FAILED:\n%s"
          % "\n".join(ps_out), flush=True)

    result["ok"] = ok and ps_ok
    if json_path:
        with open(json_path, "w") as f:
            json.dump(result, f, indent=1)
        print("wrote", json_path)
    return result


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--collective-worker":
        collective_worker(*(int(v) for v in sys.argv[2:6]))
        sys.exit(0)
    if len(sys.argv) > 1 and sys.argv[1] == "--ps-server":
        ps_server(int(sys.argv[2]), int(sys.argv[3]))
        sys.exit(0)
    if len(sys.argv) > 1 and sys.argv[1] == "--ps-worker":
        ps_worker(int(sys.argv[2]), int(sys.argv[3]), int(sys.argv[4]))
        sys.exit(0)

    p = argparse.ArgumentParser()
    p.add_argument("--n-procs", type=int, default=2)
    p.add_argument("--dev-per-proc", type=int, default=4)
    p.add_argument("--json", default=None)
    p.add_argument("--mesh", default=None,
                   help="mesh spec for the collective drill, e.g. "
                        "'dp=2,tp=4' (product must equal n_procs x "
                        "dev_per_proc); prints the resolved per-host "
                        "layout before launching")
    p.add_argument("--check-manifest", metavar="DIR", default=None,
                   help="validate a committed sharded checkpoint "
                        "offline and exit (no mesh, no processes); "
                        "nonzero exit on gaps/torn shards")
    p.add_argument("--step", type=int, default=None,
                   help="with --check-manifest: validate this step "
                        "(default: newest committed)")
    p.add_argument("--prefix", default="ckpt",
                   help="with --check-manifest: checkpoint file prefix")
    a = p.parse_args()
    if a.check_manifest:
        sys.exit(check_manifest(a.check_manifest, step=a.step,
                                prefix=a.prefix))
    r = run(a.n_procs, a.dev_per_proc, a.json, mesh=a.mesh)
    sys.exit(0 if r["ok"] else 1)
