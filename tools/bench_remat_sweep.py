"""Activation-remat policy sweep over the benchmark-of-record step.

Runs bench.build_trainer (the exact ResNet-50 program bench.py's
headline number comes from) once per remat policy and reports img/s,
peak live HBM (from XLA's cost analysis where available) and the delta
vs the no-remat baseline.  VERDICT r5 #6's done-bar: either a >=5%
img/s win lands as the new default, or the measured no-win table is
committed to docs/perf_notes.md.

Usage:
    python tools/bench_remat_sweep.py [--policies a,b,c] [--steps N]
        [--batch B] [--json out.json]

On a CPU-only box this still runs (small batch, few steps) so the
sweep machinery is testable anywhere; the committed numbers must come
from the TPU chip.
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _policies(arg):
    if arg:
        return arg.split(",")
    from mxnet_tpu.remat import list_policies

    # offload needs pinned-host support; include only on TPU
    import jax

    names = [n for n in list_policies() if not n.startswith("offload")]
    if any(d.platform == "tpu" for d in jax.devices()):
        names += [n for n in list_policies() if n.startswith("offload")]
    # 'none' first: it is the baseline every delta is computed against
    names.sort(key=lambda n: (n != "none", n))
    return names


def run_policy(policy, steps, warmup, batch):
    import jax

    import bench

    # pass 'none' through verbatim: None would fall back to the
    # MXNET_REMAT_POLICY env default and silently remat the baseline
    trainer, x, y, _batch, on_tpu = bench.build_trainer(
        batch=batch, remat_policy=policy)
    for i in range(warmup):
        loss = trainer.step([x], y)
        jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = trainer.step([x], y)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0
    ips = _batch * steps / dt
    # live-memory estimate from the compiled step (bytes accessed is the
    # roofline-relevant number; TPU runtimes also expose peak bytes)
    stats = {}
    try:
        lowered = trainer._step_fn.lower(
            trainer.param_arrays, trainer.opt_state,
            tuple(a._data if hasattr(a, "_data") else a for a in [x]),
            y._data if hasattr(y, "_data") else y,
            jax.random.PRNGKey(0))
        cost = lowered.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        for k in ("bytes accessed", "flops"):
            if k in cost:
                stats[k] = float(cost[k])
    except Exception:
        pass
    return {"policy": policy, "img_per_sec": round(ips, 2),
            "batch": _batch, "steps": steps, "on_tpu": on_tpu,
            "loss": float(loss), **stats}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--policies", default="")
    ap.add_argument("--steps", type=int,
                    default=int(os.environ.get("BENCH_STEPS", "40")))
    ap.add_argument("--warmup", type=int, default=2)
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--json", default="")
    args = ap.parse_args()

    import jax

    on_tpu = any(d.platform != "cpu" for d in jax.devices())
    steps = args.steps if on_tpu else min(args.steps, 3)
    warmup = args.warmup if on_tpu else 1

    rows = []
    for pol in _policies(args.policies):
        print("[sweep] %s ..." % pol, file=sys.stderr, flush=True)
        try:
            rows.append(run_policy(pol, steps, warmup, args.batch))
        except Exception as e:
            rows.append({"policy": pol, "error": str(e)[:200]})
        print("[sweep] %s -> %s" % (pol, rows[-1]), file=sys.stderr,
              flush=True)

    base = next((r for r in rows if r["policy"] == "none"
                 and "img_per_sec" in r), None)
    lines = ["| policy | img/s | vs none |", "|---|---|---|"]
    for r in rows:
        if "error" in r:
            lines.append("| %s | error: %s | — |"
                         % (r["policy"], r["error"]))
            continue
        rel = "%.1f%%" % (100.0 * (r["img_per_sec"] / base["img_per_sec"]
                                   - 1.0)) if base else "—"
        lines.append("| %s | %s | %s |" % (r["policy"], r["img_per_sec"],
                                           rel))
    table = "\n".join(lines)
    print(table)
    if args.json:
        with open(args.json, "w") as f:
            json.dump({"rows": rows, "table": table}, f, indent=2)


if __name__ == "__main__":
    main()
