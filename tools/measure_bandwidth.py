"""KVStore / collective bandwidth harness.

Counterpart of the reference's ``tools/bandwidth/measure.py`` (push+pull
bandwidth of a model's gradient set through the kvstore).  TPU-native
additions: the in-program path that actually carries gradients on this
stack — a jitted ``psum`` over the device mesh (ICI when real chips are
attached) — is measured alongside the host-side kvstore path and the
host<->device transfer ceiling.

Usage: python tools/measure_bandwidth.py [--network resnet50_v1]
       [--num-batches 5] [--kv-store local]
"""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import nd  # noqa: E402


def model_grad_shapes(network, num_classes, image_shape):
    from mxnet_tpu.gluon.model_zoo import vision

    net = vision.get_model(network, classes=num_classes)
    net.initialize(mx.init.Xavier())
    x = nd.array(np.zeros((1,) + image_shape, np.float32))
    net(x)  # materialize deferred shapes
    return [tuple(p.data().shape) for p in net.collect_params().values()
            if p.grad_req != "null"]


def measure_kvstore(shapes, kv_type, num_batches):
    kv = mx.kv.create(kv_type)
    grads = [nd.array(np.random.rand(*s).astype(np.float32))
             for s in shapes]
    outs = [nd.array(np.zeros(s, np.float32)) for s in shapes]
    for i, g in enumerate(grads):
        kv.init(i, nd.array(np.zeros(g.shape, np.float32)))
    total_bytes = sum(g.size for g in grads) * 4
    # warm round, drained before the timer starts (async dispatch)
    for i, (g, o) in enumerate(zip(grads, outs)):
        kv.push(i, [g])
        kv.pull(i, out=[o])
    for o in outs:
        o.asnumpy()
    t0 = time.time()
    for _ in range(num_batches):
        for i, (g, o) in enumerate(zip(grads, outs)):
            kv.push(i, [g])
            kv.pull(i, out=[o])
    for o in outs:
        o.asnumpy()
    dt = time.time() - t0
    return 2 * total_bytes * num_batches / dt / 1e9  # push+pull GB/s


def measure_psum(shapes, num_batches):
    """The real gradient-reduction path: one jitted psum over the mesh.
    On a single device the allreduce degenerates to an HBM read+write
    pass (an identity copy), which is the relevant ceiling there."""
    import jax
    import jax.numpy as jnp

    n_dev = jax.device_count()
    mesh_arrays = [jnp.asarray(np.random.rand(*s).astype(np.float32))
                   for s in shapes]

    @jax.jit
    def allreduce(tensors):
        # t + 1.0 can't be algebraically folded to an input alias (t*1.0
        # can), so single-device timing really pays the HBM read+write
        return [t + 1.0 for t in tensors]

    if n_dev > 1:
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        mesh = Mesh(np.array(jax.devices()), ("dp",))

        def ar(tensors):
            return [jax.lax.psum(t, "dp") for t in tensors]

        from mxnet_tpu.parallel import shard_map

        # args structure is the single list-typed parameter: the specs
        # pytree must be a 1-tuple wrapping the per-tensor list
        allreduce = jax.jit(
            shard_map(ar, mesh=mesh,
                      in_specs=([P()] * len(shapes),),
                      out_specs=[P()] * len(shapes)))
        mesh_arrays = [jax.device_put(a, NamedSharding(mesh, P()))
                       for a in mesh_arrays]

    total_bytes = sum(int(np.prod(s)) for s in shapes) * 4
    out = allreduce(mesh_arrays)
    jax.block_until_ready(out)
    t0 = time.time()
    for _ in range(num_batches):
        out = allreduce(mesh_arrays)
    jax.block_until_ready(out)
    dt = time.time() - t0
    return total_bytes * num_batches / dt / 1e9


def measure_transfer(shapes, num_batches):
    """Host<->device goodput, FORCED by a host-side fetch.

    Round-3 postmortem: `jax.block_until_ready` returns before tunnel
    transfers land on this platform, so the old version of this
    function reported a fictitious 2.09 GB/s upload (docs/perf_notes.md
    upload table has the measured truth: ~5-30 MB/s through the
    tunnel).  A jitted 1-element reduction whose result is fetched to
    the host cannot complete before every upload has."""
    import jax
    import jax.numpy as jnp

    hosts = [np.random.rand(*s).astype(np.float32) for s in shapes]
    total_bytes = sum(h.nbytes for h in hosts)
    force = jax.jit(
        lambda ts: sum(jnp.reshape(t, (-1,))[0] for t in ts))
    devs = [jnp.asarray(h) for h in hosts]
    float(force(devs))
    t0 = time.time()
    for _ in range(num_batches):
        devs = [jnp.asarray(h) for h in hosts]
        float(force(devs))
    up = total_bytes * num_batches / (time.time() - t0) / 1e9
    t0 = time.time()
    for _ in range(num_batches):
        _ = [np.asarray(d) for d in devs]
    down = total_bytes * num_batches / (time.time() - t0) / 1e9
    return up, down


def main():
    p = argparse.ArgumentParser(description="kvstore/collective bandwidth")
    p.add_argument("--network", default="resnet50_v1")
    p.add_argument("--num-classes", type=int, default=1000)
    p.add_argument("--image-shape", default="3,224,224")
    p.add_argument("--kv-store", default="local")
    p.add_argument("--num-batches", type=int, default=5)
    args = p.parse_args()
    shape = tuple(int(x) for x in args.image_shape.split(","))

    shapes = model_grad_shapes(args.network, args.num_classes, shape)
    total_mb = sum(int(np.prod(s)) for s in shapes) * 4 / 1e6
    print("%s: %d gradient tensors, %.1f MB" % (args.network, len(shapes),
                                                total_mb))
    gbs = measure_psum(shapes, args.num_batches)
    print("in-program allreduce (psum): %.2f GB/s" % gbs)
    up, down = measure_transfer(shapes, args.num_batches)
    print("host->device %.2f GB/s, device->host %.2f GB/s" % (up, down))
    gbs = measure_kvstore(shapes, args.kv_store, args.num_batches)
    print("kvstore(%s) push+pull: %.2f GB/s" % (args.kv_store, gbs))


if __name__ == "__main__":
    main()
