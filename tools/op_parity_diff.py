"""Mechanical op-registry diff vs the reference (VERDICT r3 next-round #5).

Extracts every operator the reference registers — ``NNVM_REGISTER_OP``,
the ``MXNET_OPERATOR_REGISTER_*`` macro family, legacy
``MXNET_REGISTER_OP_PROPERTY`` and ``add_alias`` — from its C++ sources,
and diffs that vocabulary against this repo's ``registry.list_ops()``.

Each reference op lands in exactly one bucket (the tool asserts the
bucket totals sum to the reference total — no silent skips):

- ``implemented``         — same name in our registry
- ``alias``               — covered by a registered name variant
- ``implemented_module``  — implemented as a python surface outside the
                            op registry (host-side graph/image/runtime
                            helpers), with the covering symbol recorded
- ``macro_fragment``      — a token the scraper captures from a sampling
                            macro *call site* (multisample_op.cc's
                            MXNET_OPERATOR_REGISTER_SAMPLING(distr,...))
                            that the reference never registers as a
                            user-facing op; recorded with the covering
                            op here, if any (nonzero does not fail)
- ``alias_of_implemented``— a bare back-compat name the reference *does*
                            register via add_alias and that we cover
                            only through a prefixed variant (nonzero
                            does not fail, but is reported loudly)
- ``excluded``            — deliberately not ported, with a per-category
                            reason
- ``missing``             — a real gap; the exit status fails if any

Run:  python tools/op_parity_diff.py [--json docs/op_parity.json]
The committed artifact is docs/op_parity.json.
"""
import argparse
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

REF = "/root/reference/src"

_ALIAS_PAT = re.compile(r'add_alias\("([A-Za-z0-9_.]+)"\)')
_PATTERNS = [
    re.compile(r"NNVM_REGISTER_OP\(([A-Za-z0-9_.]+)\)"),
    re.compile(r"MXNET_OPERATOR_REGISTER[A-Z0-9_]*\(\s*([A-Za-z0-9_.]+)"),
    re.compile(r"MXNET_REGISTER_OP_PROPERTY\(([A-Za-z0-9_.]+)"),
    _ALIAS_PAT,
]

# tokens captured from macro *definitions*, not registrations
_ARTIFACTS = {"name", "__name", "NAME", "distr"}


def reference_ops():
    """Returns (all captured names, names registered via add_alias).

    The alias set distinguishes genuine user-facing back-compat names
    (e.g. ``uniform``/``normal``, sample_op.cc:82,100) from bare tokens
    that only appear as macro call-site arguments."""
    names, alias_names = set(), set()
    for root, _, files in os.walk(REF):
        for f in files:
            if not f.endswith((".cc", ".cu", ".h")):
                continue
            try:
                src = open(os.path.join(root, f), errors="ignore").read()
            except OSError:
                continue
            for pat in _PATTERNS:
                captured = pat.findall(src)
                names.update(captured)
                if pat is _ALIAS_PAT:
                    alias_names.update(captured)
    return names - _ARTIFACTS, alias_names - _ARTIFACTS


# reference op -> the python surface in this repo that covers it.
# Host-side ops (graph sampling, OpenCV image helpers, engine pseudo-ops)
# live as module functions/methods rather than registry entries: their
# outputs are data-dependent-shaped or they never touch device compute.
MODULE_COVERAGE = {
    "_contrib_dgl_adjacency": "mxnet_tpu.ops.dgl_graph.dgl_adjacency",
    "_contrib_dgl_csr_neighbor_non_uniform_sample":
        "mxnet_tpu.ops.dgl_graph.dgl_csr_neighbor_non_uniform_sample",
    "_contrib_dgl_csr_neighbor_uniform_sample":
        "mxnet_tpu.ops.dgl_graph.dgl_csr_neighbor_uniform_sample",
    "_contrib_dgl_graph_compact":
        "mxnet_tpu.ops.dgl_graph.dgl_graph_compact",
    "_contrib_dgl_subgraph": "mxnet_tpu.ops.dgl_graph.dgl_subgraph",
    "_contrib_edge_id": "mxnet_tpu.ops.dgl_graph.edge_id",
    "_cvimdecode": "mxnet_tpu.image.imdecode",
    "_cvimread": "mxnet_tpu.image.imread",
    "_cvimresize": "mxnet_tpu.image.imresize",
    "_cvcopyMakeBorder": "mxnet_tpu.image.copyMakeBorder",
    "_copyto": "mxnet_tpu.ndarray.NDArray.copyto / as_in_context",
    "_CrossDeviceCopy": "mxnet_tpu.ndarray.NDArray.as_in_context",
}

EXCLUDED = {
    "runtime-internal pseudo-ops": {
        "reason": "graph-node stand-ins of the reference engine, not "
                  "user ops: provided by the corresponding subsystem "
                  "here (gluon CachedOp, autograd.Function, "
                  "operator.py custom-op plumbing, BlockGrad/stop "
                  "gradient)",
        "ops": ["_CachedOp", "_NoGradient", "_CustomFunction",
                "_NDArray", "_Native"],
    },
    "cpu/gpu vendor-library fusion internals": {
        "reason": "MKL-DNN / TensorRT subgraph ops materialized by the "
                  "reference's graph partitioner; fusion is XLA's job "
                  "on this stack (SURVEY §7, coverage row 14) and "
                  "TensorRT is CUDA-only (contrib.tensorrt documents "
                  "the non-goal)",
        "ops": ["_sg_mkldnn_conv", "_sg_mkldnn_fully_connected",
                "_trt_op"],
    },
}


def classify(ref_names, ours, ref_alias_names=()):
    alias = {}
    for n in ref_names:
        for cand in (n, n.lower(), n.replace("_contrib_", "contrib_"),
                     "_" + n, n.lstrip("_")):
            if cand != n and cand in ours:
                alias[n] = cand
                break

    explicit_excl = {o: cat for cat, d in EXCLUDED.items()
                     for o in d["ops"]}
    buckets = {"implemented": [], "alias": [], "implemented_module": {},
               "alias_of_implemented": [], "macro_fragment": [],
               "excluded": {}, "missing": []}

    def exclude(name, cat, why):
        buckets["excluded"].setdefault(
            cat, {"reason": why, "ops": []})["ops"].append(name)

    for n in sorted(ref_names):
        if (("_sample_" + n) in ref_names or ("_random_" + n) in ref_names) \
                and n not in ref_alias_names:
            # a token captured from a sampling macro *call site*
            # (MXNET_OPERATOR_REGISTER_SAMPLING(exponential, ...) pastes
            # the distribution token; the real registrations are
            # _sample_<n>/_random_<n>).  Not a user-facing reference op
            # — only ``uniform``/``normal`` get bare add_alias surfaces
            # (sample_op.cc:82,100) and those are exempted above.  We
            # register bare convenience aliases for the rest anyway (the
            # python random helpers make them reachable), but counting
            # them as "implemented reference ops" would overstate parity
            # (VERDICT r4 weak #2), so they are bucketed explicitly.
            cover = next((c for c in ("_random_" + n, "_sample_" + n, n)
                          if c in ours), None)
            buckets["macro_fragment"].append([n, cover])
            continue
        if n in ours:
            buckets["implemented"].append(n)
        elif n in MODULE_COVERAGE:
            buckets["implemented_module"][n] = MODULE_COVERAGE[n]
        elif n in explicit_excl:
            cat = explicit_excl[n]
            exclude(n, cat, EXCLUDED[cat]["reason"])
        elif n.startswith("_backward") or "_backward" in n:
            exclude(n, "backward",
                    "gradients come from XLA vjp on the forward op "
                    "(SURVEY §7: the NNVM gradient pass is delegated to "
                    "jax.grad); per-op backward registrations have no "
                    "counterpart by design")
        elif n in alias:
            tgt = alias[n]
            if n in ref_alias_names:
                buckets["alias_of_implemented"].append([n, tgt])
            else:
                buckets["alias"].append([n, tgt])
        else:
            buckets["missing"].append(n)
    return buckets


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--json", default=None)
    args = p.parse_args()

    from mxnet_tpu.ops import registry

    ours = set(registry.list_ops())
    ref, ref_aliases = reference_ops()
    buckets = classify(ref, ours, ref_aliases)
    n_excl = sum(len(v["ops"]) for v in buckets["excluded"].values())
    counts = {
        "implemented": len(buckets["implemented"]),
        "alias": len(buckets["alias"]),
        "implemented_module": len(buckets["implemented_module"]),
        "alias_of_implemented": len(buckets["alias_of_implemented"]),
        "macro_fragment": len(buckets["macro_fragment"]),
        "excluded": n_excl, "missing": len(buckets["missing"]),
    }
    # every reference name must land in exactly one bucket
    assert sum(counts.values()) == len(ref), \
        "bucket totals %d != reference total %d" % (
            sum(counts.values()), len(ref))
    print("reference ops: %d   ours: %d" % (len(ref), len(ours)))
    print("   ".join("%s: %d" % kv for kv in counts.items()))
    for n in buckets["missing"]:
        print("  MISSING", n)
    for n, cov in buckets["alias_of_implemented"]:
        print("  REF ALIAS %s covered only via %s" % (n, cov))
    if args.json:
        buckets["summary"] = dict(counts, reference_total=len(ref),
                                  ours_total=len(ours))
        with open(args.json, "w") as f:
            json.dump(buckets, f, indent=1)
        print("wrote", args.json)
    return 1 if buckets["missing"] else 0


if __name__ == "__main__":
    sys.exit(main())
