"""Query the wide-event stream: latency slices, top-K slowest, trace join.

Reads the JSONL stream written by ``mxnet_tpu/events.py``
(``MXNET_EVENTS_PATH``, or a flight-recorder bundle's ``events.json``)
and answers the questions aggregate histograms cannot:

* **slices** — p50/p99/p999 (+count, mean) of ``dur_s`` grouped by any
  event fields (``--by kind,outcome`` default; ``stage``/``reason``/
  ``error_kind``/``label``/``tenant`` work the same way);
* **top-K slowest** — the actual requests behind the tail, each with
  its ``trace_id``/``span_id`` so the row links to the span tree and
  the ``/metrics`` exemplars;
* **--join trace.json** — resolve the top-K span ids against a chrome
  trace (``tracing.export_trace`` / a flight-recorder ``trace.json``):
  prints the matched span's name, duration and child count, so "this
  request was slow" joins to "and here is what it was doing".

Multiple inputs merge into one time-ordered stream — point it at every
rank's JSONL and slice ``--by rank`` (the ``proc_id``/``n_procs``
provenance each event carries) to see which rank's latency moved:

    python tools/events_query.py events.jsonl
    python tools/events_query.py events.jsonl --kind token_request \
        --by outcome,stage --top 5
    python tools/events_query.py events.jsonl --join trace.json
    python tools/events_query.py rank*/events.jsonl --by rank,outcome

Stdlib-only on purpose (no jax import): querying evidence must stay a
sub-second operation.  Exit 0 on success, 2 on unusable input.
"""
import argparse
import json
import os
import sys


def read_events(paths):
    """Events + (path, lineno, message) problems across the inputs.
    Accepts raw JSONL streams and flight-recorder ``events.json``
    bundles ({"events": [...]}); torn lines are reported, not fatal."""
    events, problems = [], []
    for path in paths:
        try:
            with open(path, encoding="utf-8") as f:
                text = f.read()
        except OSError as e:
            problems.append((path, 0, "cannot read (%s)" % e))
            continue
        stripped = text.lstrip()
        if stripped.startswith("{") and '"events"' in stripped[:200]:
            # a flight-recorder bundle's events.json
            try:
                payload = json.loads(text)
                events.extend(e for e in payload.get("events", [])
                              if isinstance(e, dict))
                continue
            except ValueError:
                pass  # fall through to line-wise parsing
        for i, line in enumerate(text.splitlines(), 1):
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except ValueError as e:
                problems.append((path, i, "unparsable JSON (%s)" % e))
                continue
            if not isinstance(ev, dict) or "kind" not in ev:
                problems.append((path, i, "not an event object"))
                continue
            events.append(ev)
    # merge reader: with one JSONL per rank, interleave on the wall
    # clock so "what happened around t" reads pod-wide (stable sort —
    # same-timestamp events keep file order)
    events.sort(key=lambda e: e.get("time")
                if isinstance(e.get("time"), (int, float)) else 0.0)
    return events, problems


def _quantile(sorted_vals, q):
    if not sorted_vals:
        return None
    idx = int(q * len(sorted_vals))
    return sorted_vals[min(idx, len(sorted_vals) - 1)]


def _key_of(ev, fields):
    # "rank" reads the proc_id/n_procs provenance events.py records
    # (0/1 single-process), rendered r<id>/<n> so slices stay legible
    def val(f):
        if f == "rank":
            return "r%s/%s" % (ev.get("proc_id", 0), ev.get("n_procs", 1))
        return str(ev.get(f, "-"))

    return tuple(val(f) for f in fields)


def render_slices(events, fields):
    groups = {}
    for ev in events:
        groups.setdefault(_key_of(ev, fields), []).append(ev)
    header = "%-44s %7s %9s %9s %9s %9s" % (
        ",".join(fields), "count", "p50_ms", "p99_ms", "p999_ms",
        "mean_ms")
    lines = [header]
    for key in sorted(groups):
        evs = groups[key]
        durs = sorted(e["dur_s"] for e in evs
                      if isinstance(e.get("dur_s"), (int, float)))

        def ms(q):
            v = _quantile(durs, q)
            return "%.3f" % (v * 1e3) if v is not None else "-"

        mean = "%.3f" % (sum(durs) / len(durs) * 1e3) if durs else "-"
        lines.append("%-44s %7d %9s %9s %9s %9s" % (
            "/".join(key)[:44], len(evs), ms(0.5), ms(0.99), ms(0.999),
            mean))
    return lines


def render_top(events, top, span_index=None):
    timed = [e for e in events
             if isinstance(e.get("dur_s"), (int, float))]
    timed.sort(key=lambda e: -e["dur_s"])
    lines = ["top %d slowest:" % top,
             "%9s %-16s %-10s %-34s %s" % (
                 "dur_ms", "kind", "outcome", "span_id (trace_id)",
                 "detail")]
    for ev in timed[:top]:
        detail = []
        for f in ("stage", "reason", "error_kind", "label", "tokens",
                  "rows", "step"):
            if ev.get(f) is not None:
                detail.append("%s=%s" % (f, ev[f]))
        for st, v in sorted((ev.get("stages_s") or {}).items()):
            detail.append("%s=%.1fms" % (st, v * 1e3))
        lines.append("%9.3f %-16s %-10s %-34s %s" % (
            ev["dur_s"] * 1e3, ev.get("kind", "-")[:16],
            ev.get("outcome", "-")[:10],
            "%s (%s)" % (ev.get("span_id"), str(ev.get("trace_id"))[:8]),
            " ".join(detail)))
        if span_index is not None:
            sp = span_index.get(str(ev.get("span_id")))
            if sp is None:
                lines.append("%9s trace: span not found (evicted from "
                             "the ring buffer, or tracing was off)" % "")
            else:
                lines.append(
                    "%9s trace: span %r %.3f ms, %d child span(s)"
                    % ("", sp["name"], sp["dur_ms"], sp["children"]))
    return lines


def build_span_index(trace_path):
    """span_id -> {name, dur_ms, children} from a chrome trace
    (tracing.export_trace payload or a bundle's trace.json)."""
    with open(trace_path, encoding="utf-8") as f:
        payload = json.load(f)
    events = payload.get("traceEvents", payload)
    index, children = {}, {}
    for ev in events:
        if not isinstance(ev, dict):
            continue
        args = ev.get("args") or {}
        sid = args.get("span_id")
        if sid is None:
            continue
        index[str(sid)] = {"name": ev.get("name", "?"),
                           "dur_ms": float(ev.get("dur", 0.0)) / 1e3,
                           "children": 0}
        pid = args.get("parent_id")
        if pid is not None:
            children[str(pid)] = children.get(str(pid), 0) + 1
    for sid, n in children.items():
        if sid in index:
            index[sid]["children"] = n
    return index


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("paths", nargs="+",
                   help="wide-event JSONL file(s) (MXNET_EVENTS_PATH "
                        "stream or a flight-recorder events.json)")
    p.add_argument("--kind", help="only this unit-of-work kind")
    p.add_argument("--outcome", help="only this outcome "
                                     "(ok/shed/deadline/evicted/error)")
    p.add_argument("--by", default="kind,outcome",
                   help="comma list of fields to slice the latency "
                        "table by (default kind,outcome; stage/reason/"
                        "error_kind/label/model/tenant work too — "
                        "tenant slices gateway_request events per "
                        "caller, rank slices by the proc_id/n_procs "
                        "provenance across merged per-rank files)")
    p.add_argument("--top", type=int, default=10,
                   help="slowest events to list with trace ids")
    p.add_argument("--join", metavar="TRACE_JSON",
                   help="chrome trace to resolve the top-K span ids "
                        "against")
    args = p.parse_args(argv)

    events, problems = read_events(args.paths)
    for path, lineno, msg in problems:
        print("events_query: %s:%d: %s" % (path, lineno, msg),
              file=sys.stderr)
    if args.kind:
        events = [e for e in events if e.get("kind") == args.kind]
    if args.outcome:
        events = [e for e in events if e.get("outcome") == args.outcome]
    if not events:
        print("events_query: no matching events", file=sys.stderr)
        return 2
    span_index = None
    if args.join:
        if not os.path.exists(args.join):
            print("events_query: --join %s does not exist" % args.join,
                  file=sys.stderr)
            return 2
        span_index = build_span_index(args.join)
    fields = [f.strip() for f in args.by.split(",") if f.strip()]
    out = ["%d event(s)" % len(events), ""]
    if "rank" in fields:
        # event files written before rank provenance existed carry no
        # proc_id — they slice as rank 0, and we SAY so instead of
        # silently folding old data into r0
        legacy = sum(1 for e in events if "proc_id" not in e)
        if legacy:
            out.append("note: %d event(s) predate rank provenance "
                       "(no proc_id field) — defaulted to rank 0"
                       % legacy)
            out.append("")
    out.extend(render_slices(events, fields))
    out.append("")
    out.extend(render_top(events, args.top, span_index))
    print("\n".join(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
