"""Blocking vs async checkpoint overhead per train step.

Trains a small MLP with ShardedTrainer for N steps three ways — no
checkpointing, blocking saves every step, async saves every step — and
reports per-step wall time plus the derived per-save overhead.  The
async path should hide (de)serialization and fsync behind the next
step's compute; what remains visible is the synchronous host snapshot.

``--sharded`` benchmarks the pod-scale checkpoint format instead:
per-host sharded save (addressable shards only, no host gather) and
restore vs the dense host-gathered path over the same trainer state,
reporting ``checkpoint_sharded_save_seconds`` /
``checkpoint_sharded_restore_seconds`` to the perf ledger (down-good).

CPU numbers are committed in docs/fault_tolerance.md; rerun on TPU with:

    python tools/bench_checkpoint.py --params-mb 64 --steps 50
    python tools/bench_checkpoint.py --params-mb 64 --sharded
"""
import argparse
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import nd  # noqa: E402
from mxnet_tpu import checkpoint as ck  # noqa: E402
from mxnet_tpu import parallel  # noqa: E402
import mxnet_tpu.gluon as gluon  # noqa: E402
from mxnet_tpu.gluon import nn  # noqa: E402


def ledger_records(results):
    """perf_ledger record(s) for one run: the async per-save overhead
    is the headline (the number the async path exists to shrink); a
    ``--sharded`` run adds the sharded save/restore wall times (both
    down-good via the ``_seconds`` suffix); the full results ride as
    fields.  The tier-1 schema guard calls this with a canned result."""
    from mxnet_tpu import perf_ledger

    recs = []
    if "async_overhead_ms_per_save" in results:
        recs.append(perf_ledger.make_record(
            "checkpoint_async_overhead_ms_per_save",
            results["async_overhead_ms_per_save"], "ms", **results))
    if "sharded_save_s" in results:
        recs.append(perf_ledger.make_record(
            "checkpoint_sharded_save_seconds",
            results["sharded_save_s"], "s", **results))
    if "sharded_restore_s" in results:
        recs.append(perf_ledger.make_record(
            "checkpoint_sharded_restore_seconds",
            results["sharded_restore_s"], "s", **results))
    if not recs:
        raise ValueError("results carry no known headline fields")
    return recs


def make_trainer(hidden, n_layers, seed=7):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    for _ in range(n_layers):
        net.add(nn.Dense(hidden, activation="relu"))
    net.add(nn.Dense(1))
    net.initialize()
    loss_fn = gluon.loss.L2Loss()
    return parallel.ShardedTrainer(
        net, lambda o, l: loss_fn(o, l), optimizer="adam",
        optimizer_params={"learning_rate": 1e-3})


def run(trainer, steps, batch, label, manager=None, period=1):
    if manager is not None:
        trainer.attach_checkpoint_manager(manager, period=period,
                                          auto_resume=False,
                                          install_signal_handler=False)
    # warm-up compiles the step and materializes params
    float(np.asarray(trainer.step([batch], label)))
    t0 = time.perf_counter()
    for _ in range(steps):
        trainer.step([batch], label)
    if manager is not None:
        manager.wait()
    import jax

    jax.block_until_ready(trainer.param_arrays)
    dt = time.perf_counter() - t0
    trainer._ckpt_manager = None
    return dt / steps * 1e3  # ms/step


def run_sharded(hidden, n_layers, X, Y, repeats=3):
    """Sharded (per-host shards, no gather) vs dense (host-gathered)
    save + restore wall time over the SAME materialized trainer state;
    best-of-``repeats`` for each."""
    tr = make_trainer(hidden, n_layers)
    float(np.asarray(tr.step([X], Y)))   # materialize params on-mesh
    step, arrays, blobs, meta = tr._checkpoint_payload()
    out = {}
    for mode, sharded in (("gather", False), ("sharded", True)):
        d = tempfile.mkdtemp(prefix="bench_ckpt_%s_" % mode)
        try:
            m = ck.CheckpointManager(d, keep_last=2, async_save=False,
                                     sharded=sharded)
            saves, restores = [], []
            for i in range(repeats):
                t0 = time.perf_counter()
                m.save(step + i, arrays, blobs=blobs, meta=meta)
                saves.append(time.perf_counter() - t0)
                t0 = time.perf_counter()
                ckpt = m.load(step=step + i)
                restores.append(time.perf_counter() - t0)
                assert ckpt is not None and not ckpt.resharded
            out["%s_save_s" % mode] = round(min(saves), 6)
            out["%s_restore_s" % mode] = round(min(restores), 6)
        finally:
            shutil.rmtree(d, ignore_errors=True)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--params-mb", type=float, default=8.0,
                    help="approximate total parameter size")
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--period", type=int, default=1,
                    help="save every N steps")
    ap.add_argument("--sharded", action="store_true",
                    help="benchmark the sharded (pod-scale) checkpoint "
                         "format vs the dense gather path instead of "
                         "the async-overhead drill")
    ap.add_argument("--repeats", type=int, default=3,
                    help="--sharded: best-of-N save/restore timings")
    ap.add_argument("--out", default=None, help="write JSON here")
    args = ap.parse_args()

    # hidden x hidden fp32 layers: pick hidden so 4 layers ≈ params_mb
    n_layers = 4
    hidden = max(32, int((args.params_mb * 1e6 / 4 / n_layers) ** 0.5))
    rng = np.random.RandomState(0)
    X = nd.array(rng.rand(args.batch, hidden).astype(np.float32))
    Y = nd.array(rng.rand(args.batch, 1).astype(np.float32))

    results = {"params_mb": args.params_mb, "hidden": hidden,
               "n_layers": n_layers, "steps": args.steps,
               "period": args.period,
               "platform": os.environ.get("JAX_PLATFORMS", "default")}

    if args.sharded:
        results.update(run_sharded(hidden, n_layers, X, Y,
                                   repeats=args.repeats))
    else:
        tr = make_trainer(hidden, n_layers)
        results["baseline_ms"] = run(tr, args.steps, X, Y)

        for mode, async_save in (("blocking", False), ("async", True)):
            d = tempfile.mkdtemp(prefix="bench_ckpt_")
            try:
                m = ck.CheckpointManager(d, keep_last=2,
                                         async_save=async_save)
                tr = make_trainer(hidden, n_layers)
                results["%s_ms" % mode] = run(tr, args.steps, X, Y,
                                              manager=m,
                                              period=args.period)
            finally:
                shutil.rmtree(d, ignore_errors=True)

        for mode in ("blocking", "async"):
            results["%s_overhead_ms_per_save" % mode] = (
                (results["%s_ms" % mode] - results["baseline_ms"])
                * args.period)

    print(json.dumps(results, indent=2))
    from mxnet_tpu import perf_ledger

    for rec in ledger_records(results):
        perf_ledger.emit(rec)
    if args.out:
        ck.atomic_write(args.out, json.dumps(results, indent=2))


if __name__ == "__main__":
    main()
