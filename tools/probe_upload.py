"""Host->device upload bandwidth vs transfer size and dtype.

Round-3 verdict found a contradiction: tools/measure_bandwidth.py records
~2 GB/s upload (many fp32 tensors), while a single 77 MB ml_dtypes-bf16
`device_put` ran at ~6 MB/s.  This probe maps the whole surface so every
upload consumer (serving, IO pipeline) can be built on measured numbers.

Methodology: `jax.block_until_ready` can return before tunnel transfers
land (docs/perf_notes.md), so each timed upload is followed by a jitted
1-element reduction whose host fetch cannot complete before the upload
has.  The fetch's own round-trip (~ms) is measured separately and
subtracted via the smallest size.

Usage: python tools/probe_upload.py [--json out.json]
"""
import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--json", default=None)
    p.add_argument("--max-mb", type=int, default=256)
    args = p.parse_args()

    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    print("device:", dev)

    probe = jax.jit(lambda a: jnp.reshape(a, (-1,))[0].astype(jnp.float32))

    def timed_upload(x, reps=2):
        # one warm round so the probe program is compiled for this shape
        y = jax.device_put(x, dev)
        float(probe(y))
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            y = jax.device_put(x, dev)
            float(probe(y))  # forces the upload to have landed
            best = min(best, time.perf_counter() - t0)
        return best

    sizes = [2 ** k for k in range(10, 48)
             if 2 ** k <= args.max_mb * 2 ** 20]
    if len(sizes) > 8:
        big = sizes[-1]
        sizes = sizes[::2]
        if sizes[-1] != big:
            sizes.append(big)
    try:
        import ml_dtypes

        bf16 = np.dtype(ml_dtypes.bfloat16)
    except ImportError:
        bf16 = None
    dtypes = [("float32", np.float32), ("uint8", np.uint8)]
    if bf16 is not None:
        dtypes.append(("bfloat16(ml_dtypes)", bf16))

    rows = []
    print("%8s  %-20s %10s %12s" % ("bytes", "dtype", "time", "GB/s"))
    for name, dt in dtypes:
        for nbytes in sizes:
            n = nbytes // np.dtype(dt).itemsize
            if n == 0:
                continue
            x = (np.random.rand(n) * 100).astype(np.float32).astype(dt)
            t = timed_upload(x)
            gbs = nbytes / t / 1e9
            rows.append({"dtype": name, "bytes": nbytes,
                         "seconds": round(t, 6), "GBps": round(gbs, 4)})
            print("%8.1fM %-20s %9.4fs %10.3f GB/s"
                  % (nbytes / 2 ** 20, name, t, gbs))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(rows, f, indent=1)
        print("wrote", args.json)


if __name__ == "__main__":
    main()
