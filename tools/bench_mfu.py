"""Measure this chip's attainable compute ceiling and do the MFU
accounting for bench.py (VERDICT r4 weak #1 / next-round #1).

Two forced-compute probes, both timed with the platform-safe
methodology (chain iterations inside one jit program through donated
state, finish with a host float() fetch — `block_until_ready` returns
early on the tunneled device):

1. matmul ceiling — bf16 square matmul chains at several MXU-friendly
   sizes; the peak is the chip's practical TF/s for pure MXU work.
2. conv ceiling — a chained 3x3 same-channel convolution (the ResNet-50
   hot shape class) at bf16; convs lower to implicit GEMM on the MXU
   but pay layout/im2col overheads, so this is the fairer ceiling for
   a conv net.

Then computes MFU for the bench.py headline (img/s x FLOPs/img) against
(a) the measured matmul ceiling, (b) the measured conv ceiling, and
(c) the v5e paper peak (197 TF/s bf16).

Run on an idle chip:  python tools/bench_mfu.py [--json docs/mfu_probe.json]
"""
import argparse
import json
import sys
import time
from functools import partial

import numpy as np

# ResNet-50 v1 @224: ~4.1 GFLOP forward per image; training fwd+bwd+update
# is conventionally 3x forward (the reference's own accounting in
# docs/faq/perf.md benchmarks uses images/sec on the same model).
RESNET50_TRAIN_GFLOP_PER_IMG = 12.3
V5E_PAPER_PEAK_TFLOPS = 197.0


def log(msg):
    print("[mfu %6.1fs] %s" % (time.time() - T0, msg), file=sys.stderr,
          flush=True)


def _timed_chain(fn, state, fetch, repeats=3):
    """Run fn (a jitted donated-state chain) `repeats` times; return
    (best_seconds, final_state).  fetch(state) must force completion
    with a host round-trip."""
    best = float("inf")
    for _ in range(repeats):
        t0 = time.time()
        state = fn(state)
        fetch(state)
        best = min(best, time.time() - t0)
    return best, state


def matmul_ceiling(sizes=(2048, 4096, 8192), iters=256):
    import jax
    import jax.numpy as jnp
    from jax import lax

    results = []
    for n in sizes:
        flops_per = 2.0 * n * n * n

        @partial(jax.jit, donate_argnums=0)
        def chain(y, w):
            def body(_, y):
                # the 0.03 scale keeps bf16 activations bounded; it
                # fuses into the matmul epilogue (no extra HBM pass)
                return (y @ w) * jnp.asarray(0.03, jnp.bfloat16)

            return lax.fori_loop(0, iters, body, y)

        rng = np.random.RandomState(0)
        y = jnp.asarray(rng.randn(n, n), jnp.bfloat16)
        w = jnp.asarray(rng.randn(n, n) / np.sqrt(n), jnp.bfloat16)

        def fetch(s):
            return float(jnp.mean(jnp.abs(s).astype(jnp.float32)))

        log("matmul %d: compiling" % n)
        y = chain(y, w)
        fetch(y)  # warm-up + compile outside the clock
        secs, y = _timed_chain(lambda s: chain(s, w), y, fetch)
        tflops = iters * flops_per / secs / 1e12
        log("matmul %d: %.1f TF/s (%.2fs / %d iters)"
            % (n, tflops, secs, iters))
        results.append({"n": n, "iters": iters, "seconds": secs,
                        "tflops": tflops})
    return results


def conv_ceiling(batch=256, hw=28, ch=256, iters=128):
    import jax
    import jax.numpy as jnp
    from jax import lax

    flops_per = 2.0 * batch * hw * hw * ch * ch * 9

    @partial(jax.jit, donate_argnums=0)
    def chain(x, w):
        def body(_, x):
            y = lax.conv_general_dilated(
                x, w, window_strides=(1, 1), padding="SAME",
                dimension_numbers=("NCHW", "OIHW", "NCHW"))
            return y * jnp.asarray(0.03, jnp.bfloat16)

        return lax.fori_loop(0, iters, body, x)

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(batch, ch, hw, hw), jnp.bfloat16)
    w = jnp.asarray(rng.randn(ch, ch, 3, 3) / (3 * np.sqrt(ch)),
                    jnp.bfloat16)

    def fetch(s):
        return float(jnp.mean(jnp.abs(s).astype(jnp.float32)))

    log("conv %dx%dx%dx%d: compiling" % (batch, ch, hw, hw))
    x = chain(x, w)
    fetch(x)
    secs, x = _timed_chain(lambda s: chain(s, w), x, fetch)
    tflops = iters * flops_per / secs / 1e12
    log("conv: %.1f TF/s (%.2fs / %d iters)" % (tflops, secs, iters))
    return {"batch": batch, "hw": hw, "ch": ch, "iters": iters,
            "seconds": secs, "tflops": tflops}


def hbm_bandwidth(mb=512, iters=64):
    """Forced elementwise chain: one read + one write of `mb` MB per
    iteration -> effective HBM GB/s (the memory roofline)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    n = mb * 1024 * 1024 // 2  # bf16 elements
    bytes_per_iter = 2.0 * n * 2  # read + write

    @partial(jax.jit, donate_argnums=0)
    def chain(y):
        def body(_, y):
            return y * jnp.asarray(1.0001, jnp.bfloat16) \
                + jnp.asarray(0.0001, jnp.bfloat16)

        return lax.fori_loop(0, iters, body, y)

    y = jnp.ones((n,), jnp.bfloat16)

    def fetch(s):
        return float(s[:8].astype(jnp.float32).sum())

    log("hbm %dMB: compiling" % mb)
    y = chain(y)
    fetch(y)
    # the shared tunnel chip shows 2x session variance on this probe
    # (314-603 GB/s observed); take the best of several repeats
    secs, y = _timed_chain(chain, y, fetch, repeats=6)
    gbs = iters * bytes_per_iter / secs / 1e9
    log("hbm: %.0f GB/s (%.2fs / %d iters)" % (gbs, secs, iters))
    return {"mb": mb, "iters": iters, "seconds": secs, "gb_per_s": gbs}


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--json", default=None)
    p.add_argument("--bench-img-per-sec", type=float, default=None,
                   help="override the bench.py img/s used for MFU "
                        "(default: latest BENCH_r*.json in cwd)")
    args = p.parse_args()

    import jax

    log("devices: %s" % jax.devices())

    mm = matmul_ceiling()
    cv = conv_ceiling()
    bw = hbm_bandwidth()

    img_s = args.bench_img_per_sec
    if img_s is None:
        import glob

        benches = sorted(glob.glob("BENCH_r*.json"))
        if benches:
            with open(benches[-1]) as f:
                img_s = json.load(f).get("parsed", {}).get("value")
    bench_tflops = (img_s or 0) * RESNET50_TRAIN_GFLOP_PER_IMG / 1e3

    mm_peak = max(r["tflops"] for r in mm)
    out = {
        "matmul": mm,
        "conv": cv,
        "hbm": bw,
        "bench_img_per_sec": img_s,
        "bench_tflops": bench_tflops,
        "mfu_vs_matmul_ceiling": bench_tflops / mm_peak if img_s else None,
        "mfu_vs_conv_ceiling": bench_tflops / cv["tflops"]
        if img_s else None,
        "mfu_vs_v5e_paper_peak": bench_tflops / V5E_PAPER_PEAK_TFLOPS
        if img_s else None,
        "v5e_paper_peak_tflops": V5E_PAPER_PEAK_TFLOPS,
        "resnet50_train_gflop_per_img": RESNET50_TRAIN_GFLOP_PER_IMG,
    }
    print(json.dumps(out, indent=1))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=1)
        log("wrote %s" % args.json)


T0 = time.time()

if __name__ == "__main__":
    main()
