"""Benchmark: fused vs unfused graphs for every registered fusion
pattern (symbol/fusion.py registry), BENCH-comparable output.

For each pattern x shape the canonical chain (the same
``FusionPattern.bench_builder`` the autotuner and the tier-1 parity
guard use) is bound twice — stock graph vs force-fused — and timed for
forward (inference) and forward+backward (training).  One BENCH-marked
perf_ledger record per measurement goes to stdout (and to the
MXNET_PERF_LEDGER run ledger when set)::

    BENCH {"metric": "fusion_layer_norm_fast_256x4096_train_speedup",
           "value": 1.72, "unit": "x", ...}

plus a headline ``fusion_best_speedup`` line — train-mode only (the
acceptance gate: >=1.10 fwd+bwd on at least one elementwise chain).
Progress to stderr.

    python tools/bench_fusion.py [--patterns a,b] [--shapes 64x1024 ...]
        [--iters 30] [--json out.json]
"""
import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

_T0 = time.time()


def log(msg):
    print("[bench_fusion %6.1fs] %s" % (time.time() - _T0, msg),
          file=sys.stderr, flush=True)


def ledger_records(rows):
    """perf_ledger record(s) for measured rows (each already carries
    metric/value/unit).  The tier-1 schema guard calls this with a
    canned row list."""
    from mxnet_tpu import perf_ledger

    recs = []
    for row in rows:
        fields = {k: v for k, v in row.items()
                  if k not in ("metric", "value", "unit")}
        recs.append(perf_ledger.make_record(
            row["metric"], row["value"], row["unit"], **fields))
    return recs


def main(argv=None):
    p = argparse.ArgumentParser(
        description="Measure fused-vs-unfused speedups per pattern/shape")
    p.add_argument("--patterns", help="comma list (default: all)")
    p.add_argument("--shapes", nargs="*",
                   help="shapes like 64x1024 (default: per-pattern "
                        "bench_shapes)")
    p.add_argument("--iters", type=int, default=30)
    p.add_argument("--json", help="also write all rows to this file")
    args = p.parse_args(argv)

    log("importing jax/mxnet_tpu")
    import jax

    import mxnet_tpu  # noqa: F401
    from mxnet_tpu import perf_ledger
    from mxnet_tpu.symbol import fusion as F

    log("devices=%s" % (jax.devices(),))
    names = ([n for n in args.patterns.split(",") if n]
             if args.patterns else F.list_patterns())
    shapes = None
    if args.shapes:
        shapes = [tuple(int(d) for d in s.lower().split("x"))
                  for s in args.shapes]

    rows = []
    best = None
    for name in names:
        pattern = F.get_pattern(name)
        if pattern.bench_builder is None:
            continue
        for shape in (shapes or pattern.bench_shapes):
            log("measuring %s @ %s" % (name, shape))
            try:
                res = F.microbench(name, shape, iters=args.iters)
            except Exception as e:
                log("skip %s @ %s: %s" % (name, shape, e))
                continue
            if not res["fired"]:
                log("WARNING: %s did not match its own chain at %s"
                    % (name, shape))
                continue
            tag = "%s_%s" % (name, "x".join(str(d) for d in shape))
            row = {
                "metric": "fusion_%s_train_speedup" % tag,
                "value": round(res["speedup"], 3),
                "unit": "x",
                "fused_ms": round(res["fused_train_ms"], 4),
                "unfused_ms": round(res["unfused_train_ms"], 4),
                "infer_speedup": round(res["speedup_infer"], 3),
                "key": res["key"],
            }
            rows.append(row)
            # emit AS MEASURED: a killed mid-sweep run keeps every
            # completed row on stdout and in the ledger
            perf_ledger.emit(ledger_records([row])[0])
            # headline is TRAIN-ONLY: the acceptance gate is a
            # training-path win, an inference-only win must not pass it
            if best is None or res["speedup"] > best["value"]:
                best = {"metric": "fusion_best_speedup",
                        "value": round(res["speedup"], 3), "unit": "x",
                        "pattern": name, "mode": "train",
                        "shape": "x".join(str(d) for d in shape)}
    if best is not None:
        rows.append(best)
        perf_ledger.emit(ledger_records([best])[0])
    if args.json:
        from mxnet_tpu.checkpoint import atomic_write

        atomic_write(args.json, json.dumps(
            {"backend": jax.default_backend(), "iters": args.iters,
             "rows": rows}, indent=2))
        log("wrote %s" % args.json)
    return 0


if __name__ == "__main__":
    sys.exit(main())
