"""Where did the milliseconds go: the perf-ledger reporter.

Reads the append-only JSONL run ledger every bench emitter writes
through ``mxnet_tpu/perf_ledger.py`` and renders

* **one run** — every metric row plus the step-time attribution table
  (device_compute / compile / aot_load / data_wait / host_other,
  ms/step and share of wall), optionally merged with a unified chrome
  trace (``--trace``: top span aggregates) and a telemetry JSON dump
  (``--telemetry``: the step/gap/compile families);
* **a delta between two runs** (``--diff A B``) — per-metric change
  with its noise-free attribution story ("device_compute -4.1%,
  host_other +9.3%"), the decision view the on-chip payoff sweep
  flips defaults from;
* **--backfill** — ingests the pre-schema run files (BENCH_r0*.json
  driver captures, MULTICHIP/MULTIHOST dryrun artifacts) into the
  ledger with provenance marked ``unknown``, so the r02-r05 flat-line
  is queryable history instead of dead files.

Stdlib-only on purpose (perf_ledger is loaded standalone, no jax
import): reporting the history must stay a sub-second operation.

    python tools/perf_report.py --ledger perf_ledger.jsonl
    python tools/perf_report.py --ledger perf_ledger.jsonl --run a1b2c3
    python tools/perf_report.py --ledger perf_ledger.jsonl --diff A B
    python tools/perf_report.py --ledger perf_ledger.jsonl \
        --backfill BENCH_r0*.json MULTICHIP_r0*.json MULTIHOST_r0*.json
"""
import argparse
import importlib.util
import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


def load_perf_ledger():
    """Load mxnet_tpu/perf_ledger.py WITHOUT importing the package (no
    jax): the module is stdlib-only at import time by contract."""
    path = os.path.join(REPO, "mxnet_tpu", "perf_ledger.py")
    spec = importlib.util.spec_from_file_location("_perf_ledger", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


pl = load_perf_ledger()


# ---------------------------------------------------------------------------
# backfill: pre-schema run files -> ledger rows
# ---------------------------------------------------------------------------

def backfill_file(path):
    """Records for one legacy run artifact.  Recognized shapes:

    * driver bench captures (``BENCH_r0*.json``): ``parsed`` when the
      driver extracted the JSON line, else the stdout ``tail`` is
      scanned with the legacy brace heuristic;
    * multichip dryruns (``n_devices``/``ok``): a 0/1 pass metric;
    * multihost dryruns (``n_procs``/``ok``): same;
    * anything already carrying ``metric``/``value``: passed through.

    Provenance is all ``unknown`` (that is the point of the schema
    field: absence of provenance is now explicit, not implied)."""
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    run_id = os.path.splitext(os.path.basename(path))[0]
    prov = {k: pl._UNKNOWN for k in pl.PROVENANCE_KEYS}
    mtime = round(os.path.getmtime(path), 3)

    def rec(metric, value, unit, **fields):
        r = {"schema_version": pl.SCHEMA_VERSION, "run_id": run_id,
             "time": mtime, "metric": str(metric), "value": value,
             "unit": str(unit), "provenance": dict(prov),
             "source": os.path.basename(path), "backfill": True}
        r.update(fields)
        return r

    out = []
    if not isinstance(data, dict):
        return out
    if "tail" in data and ("parsed" in data or "cmd" in data):
        rows = []
        if isinstance(data.get("parsed"), dict) and \
                data["parsed"].get("metric"):
            rows = [data["parsed"]]
        else:
            rows = pl.parse_bench_lines(data.get("tail") or "")
        for row in rows:
            fields = {k: v for k, v in row.items()
                      if k not in ("metric", "value", "unit")}
            out.append(rec(row["metric"], row.get("value"),
                           row.get("unit", pl._UNKNOWN),
                           rc=data.get("rc"), **fields))
        if not rows:
            # a timed-out/failed round is itself history worth keeping
            out.append(rec("bench_run_ok",
                           1.0 if data.get("rc") == 0 else 0.0, "bool",
                           rc=data.get("rc")))
    elif "n_devices" in data:
        out.append(rec("multichip_dryrun_ok",
                       1.0 if data.get("ok") else 0.0, "bool",
                       n_devices=data.get("n_devices"),
                       rc=data.get("rc"),
                       skipped=data.get("skipped")))
    elif "n_procs" in data:
        out.append(rec("multihost_dryrun_ok",
                       1.0 if data.get("ok") else 0.0, "bool",
                       n_procs=data.get("n_procs"),
                       dev_per_proc=data.get("dev_per_proc"),
                       topology=data.get("topology")))
    elif data.get("metric") is not None:
        fields = {k: v for k, v in data.items()
                  if k not in ("metric", "value", "unit")}
        out.append(rec(data["metric"], data.get("value"),
                       data.get("unit", pl._UNKNOWN), **fields))
    return [r for r in out if not pl.validate_record(r)]


def backfill(paths, ledger):
    total = 0
    for path in paths:
        try:
            recs = backfill_file(path)
        except (OSError, ValueError) as e:
            print("backfill: %s: unreadable (%s)" % (path, e),
                  file=sys.stderr)
            continue
        if not recs:
            print("backfill: %s: no ingestible records" % path,
                  file=sys.stderr)
            continue
        pl.append(recs, path=ledger)
        total += len(recs)
        print("backfill: %s -> %d record(s)" % (path, len(recs)))
    print("backfill: %d record(s) appended to %s" % (total, ledger))
    return 0 if total else 2


# ---------------------------------------------------------------------------
# report rendering
# ---------------------------------------------------------------------------

def group_runs(records):
    """run_id -> [records], ordered by each run's first timestamp."""
    runs = {}
    for r in records:
        runs.setdefault(r["run_id"], []).append(r)
    return dict(sorted(runs.items(),
                       key=lambda kv: min(r["time"] for r in kv[1])))


def _fmt_val(v):
    if v is None:
        return "-"
    if isinstance(v, float):
        return "%.4g" % v
    return str(v)


def _attribution_of(recs):
    """The run's attribution dict (first record that carries one)."""
    for r in recs:
        if isinstance(r.get("attribution"), dict):
            return r["attribution"]
    return None


def render_run(run_id, recs, trace=None, telemetry=None):
    lines = ["run %s (%d record(s))" % (run_id, len(recs))]
    prov = recs[0].get("provenance", {})
    lines.append("  provenance: git=%s jax=%s backend=%s x%s "
                 "dtype=%s aot=%s"
                 % (str(prov.get("git_sha"))[:12], prov.get("jax_version"),
                    prov.get("backend"), prov.get("device_count"),
                    prov.get("dtype_policy"), prov.get("aot")))
    lines.append("  %-48s %14s  %s" % ("metric", "value", "unit"))
    for r in sorted(recs, key=lambda r: r["metric"]):
        lines.append("  %-48s %14s  %s"
                     % (r["metric"][:48], _fmt_val(r["value"]), r["unit"]))
    attr = _attribution_of(recs)
    if attr:
        wall = attr.get("wall_ms_per_step") or 0.0
        lines.append("  where did the milliseconds go "
                     "(%s steps, %.3f ms wall/step):"
                     % (attr.get("steps", "?"), wall))
        buckets = _buckets_of(attr)
        order = [b for b in pl.BREAKDOWN_BUCKETS if b in buckets] + \
            sorted(set(buckets) - set(pl.BREAKDOWN_BUCKETS))
        for name in order:
            ms = buckets[name]
            share = 100.0 * ms / wall if wall else 0.0
            lines.append("    %-15s %10.3f ms  %5.1f%%"
                         % (name, ms, share))
    if trace:
        lines.extend(render_trace(trace))
    if telemetry:
        lines.extend(render_telemetry(telemetry))
    return lines


def render_trace(path, top=10):
    """Top span aggregates of a unified chrome trace (tracing.py
    export or a flight-recorder bundle's trace.json)."""
    with open(path, encoding="utf-8") as f:
        payload = json.load(f)
    events = payload.get("traceEvents", payload)
    agg = {}
    for ev in events:
        if not isinstance(ev, dict) or ev.get("ph") != "X":
            continue
        name = ev.get("name", "?")
        tot, n = agg.get(name, (0.0, 0))
        agg[name] = (tot + float(ev.get("dur", 0.0)), n + 1)
    lines = ["  trace spans (%s; top %d by total time):"
             % (os.path.basename(path), top)]
    for name, (tot, n) in sorted(agg.items(), key=lambda kv: -kv[1][0])[
            :top]:
        lines.append("    %-32s %10.3f ms total  x%d  (%.3f ms avg)"
                     % (name[:32], tot / 1e3, n, tot / 1e3 / max(n, 1)))
    return lines


_TELEMETRY_FAMILIES = ("mxnet_tpu_train_step_seconds",
                       "mxnet_tpu_host_gap_seconds",
                       "mxnet_tpu_device_prefetch_wait_seconds",
                       "mxnet_tpu_compile_seconds",
                       "mxnet_tpu_aot_load_seconds",
                       "mxnet_tpu_train_steps_total",
                       "mxnet_tpu_train_mfu_ratio")


def render_telemetry(path):
    """The attribution-relevant families of a telemetry.dump() JSON."""
    with open(path, encoding="utf-8") as f:
        snap = json.load(f)
    metrics = snap.get("metrics", {})
    lines = ["  telemetry (%s):" % os.path.basename(path)]
    for name in _TELEMETRY_FAMILIES:
        fam = metrics.get(name)
        if not fam:
            continue
        for s in fam.get("series", []):
            label = ",".join("%s=%s" % kv
                             for kv in (s.get("labels") or {}).items())
            if fam["type"] == "histogram":
                cnt = s.get("count", 0)
                mean = (s.get("sum", 0.0) / cnt) if cnt else 0.0
                lines.append("    %-44s count=%-6d mean=%.6fs"
                             % ("%s{%s}" % (name, label), cnt, mean))
            else:
                lines.append("    %-44s %s"
                             % ("%s{%s}" % (name, label),
                                _fmt_val(s.get("value"))))
    return lines


def render_diff(run_a, recs_a, run_b, recs_b):
    """Per-metric delta + the attributed milliseconds story."""
    lines = ["delta %s -> %s" % (run_a, run_b)]
    by_a = {r["metric"]: r for r in recs_a}
    by_b = {r["metric"]: r for r in recs_b}
    lines.append("  %-48s %12s %12s %9s" % ("metric", run_a[:12],
                                            run_b[:12], "delta"))
    for m in sorted(set(by_a) & set(by_b)):
        va, vb = by_a[m]["value"], by_b[m]["value"]
        delta = "-"
        if isinstance(va, (int, float)) and isinstance(vb, (int, float)) \
                and va:
            delta = "%+.1f%%" % (100.0 * (vb - va) / abs(va))
        lines.append("  %-48s %12s %12s %9s"
                     % (m[:48], _fmt_val(va), _fmt_val(vb), delta))
    only_a = sorted(set(by_a) - set(by_b))
    only_b = sorted(set(by_b) - set(by_a))
    if only_a:
        lines.append("  only in %s: %s" % (run_a, ", ".join(only_a)))
    if only_b:
        lines.append("  only in %s: %s" % (run_b, ", ".join(only_b)))
    attr_a, attr_b = _attribution_of(recs_a), _attribution_of(recs_b)
    if attr_a or attr_b:
        # one-sided attribution is the NORMAL case against backfilled
        # pre-schema history (provenance=unknown rows carry none):
        # missing buckets read as zero so the story still renders,
        # instead of raising / silently dropping the whole section
        ba = _buckets_of(attr_a)
        bb = _buckets_of(attr_b)
        lines.append("  attribution (ms/step%s):"
                     % ("; %s has none, read as zero"
                        % (run_a if not ba else run_b)
                        if not (ba and bb) else ""))
        parts = []
        names = [n for n in pl.BREAKDOWN_BUCKETS
                 if n in ba or n in bb] or list(pl.BREAKDOWN_BUCKETS)
        names += sorted((set(ba) | set(bb)) - set(names))
        for name in names:
            a, b = ba.get(name, 0.0), bb.get(name, 0.0)
            pct = (100.0 * (b - a) / a) if a else (100.0 if b else 0.0)
            lines.append("    %-15s %10.3f -> %10.3f  (%+.1f%%)"
                         % (name, a, b, pct))
            if abs(b - a) > 1e-9:
                parts.append("%s %+.1f%%" % (name, pct))
        if parts:
            lines.append("  story: " + ", ".join(parts))
    return lines


def _buckets_of(attr):
    """The buckets_ms_per_step dict of one side's attribution, {} when
    the side has no attribution or a malformed one (backfilled rows)."""
    if not isinstance(attr, dict):
        return {}
    buckets = attr.get("buckets_ms_per_step")
    if not isinstance(buckets, dict):
        return {}
    return {k: v for k, v in buckets.items()
            if isinstance(v, (int, float))}


def goodput_report(job_dir, ledger=None):
    """--goodput: render the job-lifetime goodput/badput report (the
    same numbers /goodputz and the goodput statusz subsystem serve)
    and, with --ledger, append the schema-valid goodput records."""
    sys.path.insert(0, HERE)
    from goodputz import load_goodput

    gp = load_goodput()
    payload = gp.goodputz(dir=job_dir)
    print(gp.render_report(payload))
    if not payload.get("active"):
        print("perf_report: goodput: %s"
              % payload.get("error", "inactive"), file=sys.stderr)
        return 2
    if not payload.get("n_incarnations"):
        print("perf_report: goodput: no incarnation ledgers in %s"
              % job_dir, file=sys.stderr)
        return 2
    if ledger:
        recs = gp.ledger_records(payload)
        pl.append(recs, path=ledger)
        print("appended %d goodput record(s) to %s"
              % (len(recs), ledger))
    return 0


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--ledger",
                   help="JSONL run ledger (perf_ledger.emit appends; "
                        "MXNET_PERF_LEDGER names it for bench runs); "
                        "required except with --goodput")
    p.add_argument("--run", help="report only this run id "
                                 "(default: every run, newest last)")
    p.add_argument("--diff", nargs=2, metavar=("RUN_A", "RUN_B"),
                   help="attributed delta between two run ids "
                        "('latest'/'prev' resolve positionally)")
    p.add_argument("--backfill", nargs="+", metavar="FILE",
                   help="ingest legacy run files (BENCH_r0*.json / "
                        "MULTICHIP / MULTIHOST) into the ledger")
    p.add_argument("--trace", help="unified chrome trace to merge into "
                                   "the single-run view")
    p.add_argument("--telemetry", help="telemetry.dump() JSON to merge "
                                       "into the single-run view")
    p.add_argument("--goodput", metavar="JOB_DIR",
                   help="render the job-lifetime goodput report for "
                        "this goodput dir (goodput.py ledgers); with "
                        "--ledger, also appends the schema-valid "
                        "goodput_pct/lost-work records so the bench "
                        "history carries the job-level view")
    args = p.parse_args(argv)

    if args.goodput:
        return goodput_report(args.goodput, args.ledger)

    if args.ledger is None:
        p.error("--ledger is required (except with --goodput)")

    if args.backfill:
        return backfill(args.backfill, args.ledger)

    if not os.path.exists(args.ledger):
        print("perf_report: ledger %s does not exist" % args.ledger,
              file=sys.stderr)
        return 2
    records, problems = pl.read_ledger(args.ledger)
    for lineno, msg in problems:
        print("perf_report: %s:%d: %s" % (args.ledger, lineno, msg),
              file=sys.stderr)
    if not records:
        print("perf_report: no valid records in %s" % args.ledger,
              file=sys.stderr)
        return 2
    runs = group_runs(records)
    ids = list(runs)

    def resolve(token):
        if token == "latest":
            return ids[-1]
        if token == "prev":
            if len(ids) < 2:
                # a one-run ledger has no previous run; silently
                # diffing the run against itself would read as "no
                # change" where no comparison exists
                print("perf_report: 'prev' needs at least two runs in "
                      "the ledger (have %d)" % len(ids),
                      file=sys.stderr)
                return None
            return ids[-2]
        if token in runs:
            return token
        print("perf_report: unknown run id %r (have: %s)"
              % (token, ", ".join(ids)), file=sys.stderr)
        return None

    out = []
    if args.diff:
        a, b = resolve(args.diff[0]), resolve(args.diff[1])
        if a is None or b is None:
            return 2
        out = render_diff(a, runs[a], b, runs[b])
    elif args.run:
        rid = resolve(args.run)
        if rid is None:
            return 2
        out = render_run(rid, runs[rid], trace=args.trace,
                         telemetry=args.telemetry)
    else:
        for rid in ids:
            out.extend(render_run(rid, runs[rid]))
            out.append("")
        # a merged trace/telemetry view only makes sense for one run
        if args.trace:
            out.extend(render_trace(args.trace))
        if args.telemetry:
            out.extend(render_telemetry(args.telemetry))
    print("\n".join(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
