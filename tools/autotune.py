"""Trace-guided fusion autotuner: measure fused-vs-unfused per shape,
persist the shape-keyed cost table `symbol/fusion.py` consults at bind.

Tuning replays the PR 5 unified timeline to rank where the time and
HBM traffic actually go, then micro-benchmarks every registered fusion
pattern's canonical chain (``FusionPattern.bench_builder``) fused vs
unfused per input shape on the *current* backend, and writes the table
atomically (``checkpoint.atomic_write``)::

    python tools/autotune.py --out docs/fusion_cost_cpu.json \
        [--trace trace.json] [--patterns add_act,layer_norm_fast] \
        [--shapes 64x1024 256x4096] [--iters 20] [--lm]

``--lm`` additionally profiles the transformer-LM bench model
(tools/bench_lm.py) live: its hot-op timeline ranking lands in the
table meta and its attention/matmul operand shapes join every
pattern's microbench — the second hot-path profile next to the
ResNet-50 trace (ROADMAP sharding follow-on).

``--trace`` takes a ``tracing.export_trace`` / ``profiler.dump()`` /
flight-recorder artifact; its op-timeline ranking (total time + est.
HBM bytes from the XLA cost table — the same view as
``trace_view.py --top-ops``) is printed and embedded in the table meta
so a tuning run documents *why* those rewrites matter on that run.

Validation mode mirrors telemetry_dump's behavior — nonzero exit on
malformed input, loud but zero on stale entries::

    python tools/autotune.py --check table.json [--max-age-days 90]
"""
import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))  # trace_view (shared ranking)


def log(msg):
    print("[autotune] %s" % msg, file=sys.stderr, flush=True)


def rank_trace_ops(path, top=10):
    """(name, total_ms, calls, est_bytes|None) rows from a unified
    chrome-trace export, most expensive first — the exact
    ``trace_view.py --top-ops`` ranking (shared aggregation)."""
    try:
        with open(path) as f:
            data = json.load(f)
    except OSError as e:
        raise SystemExit("%s: cannot read (%s)" % (path, e))
    except ValueError as e:
        raise SystemExit("%s: malformed JSON (%s)" % (path, e))
    if not isinstance(data, dict) or "traceEvents" not in data:
        raise SystemExit("%s: not a chrome trace (no 'traceEvents')" % path)
    import trace_view

    return trace_view.aggregate_op_costs(data)[:top]


def run_check(path, max_age_days):
    from mxnet_tpu import fusion_cost as fc

    try:
        with open(path) as f:
            data = json.load(f)
    except OSError as e:
        print("%s: cannot read (%s)" % (path, e), file=sys.stderr)
        return 1
    except ValueError as e:
        print("%s: malformed JSON (%s)" % (path, e), file=sys.stderr)
        return 1
    problems, stale = fc.validate_table(data, max_age_days=max_age_days)
    entries = data.get("entries") if isinstance(data, dict) else None
    n = len(entries) if isinstance(entries, dict) else 0
    print("%s: %d entries, backend=%s, created=%s"
          % (path, n, data.get("backend", "?") if isinstance(data, dict)
             else "?",
             data.get("created", "?") if isinstance(data, dict) else "?"))
    for msg in stale:
        print("STALE: %s" % msg)
    for msg in problems:
        print("MALFORMED: %s" % msg, file=sys.stderr)
    return 1 if problems else 0


def profile_lm(args):
    """Run the transformer-LM bench model (tools/bench_lm.py) for a few
    steps under the unified trace and return its hot-op ranking plus
    the LM's matmul/attention operand shapes — the second hot-path
    profile the cost-table machinery has been waiting for (ROADMAP).
    The shapes feed every pattern's microbench next to its canonical
    ``bench_shapes``, so the table carries measured fused-vs-unfused
    numbers at the sizes the LM actually runs."""
    import tempfile

    import jax

    import bench_lm
    from mxnet_tpu import profiler, telemetry, tracing

    tracing.enable()
    profiler.set_config(aggregate_stats=True)
    telemetry.enable()
    log("profiling transformer-LM bench model (%d steps, mesh=%s)"
        % (args.lm_steps, args.lm_mesh or "single-device"))
    trainer, tokens, labels, cfg = bench_lm.build_lm_trainer(
        mesh=args.lm_mesh)
    xs, ys = trainer.shard_batch(tokens, labels)
    loss = None
    for _ in range(max(1, args.lm_steps)):
        loss = trainer.step([xs], ys)
    jax.block_until_ready(loss)
    path = os.path.join(tempfile.mkdtemp(prefix="mxnet_tpu_lm_"),
                        "lm_trace.json")
    tracing.export_trace(path)
    hot = rank_trace_ops(path)
    B, S, D = cfg["batch"], cfg["seq"], cfg["d_model"]
    # the LM's three dominant GEMM operand shapes: attention/residual
    # projections (B*S x D), the 4x MLP hidden (B*S x 4D), and the
    # vocab head (B*S x V)
    shapes = [(B * S, D), (B * S, 4 * D), (B * S, cfg["vocab"])]
    meta = {"model": {k: cfg[k] for k in ("vocab", "d_model", "n_heads",
                                          "n_layers", "seq", "batch")},
            "mesh": args.lm_mesh, "steps": args.lm_steps,
            "shapes": [list(s) for s in shapes],
            "trace": path,
            "hot_ops": [{"name": n, "total_ms": round(ms, 3), "calls": c,
                         "est_hbm_bytes": est}
                        for n, ms, c, est in hot]}
    return meta, hot, shapes


def profile_decode(args):
    """Run the KV-cache decode engine (tools/bench_decode.py model) for
    a few steps under the unified trace and return its hot-op ranking
    plus the SMALL-BATCH, cache-length-keyed operand shapes decode
    actually runs — token-step GEMMs are (slots x d_model)-thin and
    the attention softmax·V chain is keyed by the ring length, shapes
    the train-profile corpus never sees."""
    import tempfile

    import jax

    import bench_decode
    from mxnet_tpu import generate, profiler, telemetry, tracing

    tracing.enable()
    profiler.set_config(aggregate_stats=True)
    telemetry.enable()
    log("profiling KV-cache decode engine (%d steps)"
        % args.decode_steps)
    lm, cfg = bench_decode.build_lm(max_len=args.decode_cache_len)
    eng = generate.GenerationEngine(
        lm, slots=args.decode_slots, cache_len=args.decode_cache_len,
        dtype_policy=args.dtype_policy)
    import numpy as np

    rng = np.random.RandomState(0)
    for s in range(min(eng.slots, 4)):
        eng.admit(rng.randint(0, cfg["vocab"], 8))
    out = None
    for _ in range(max(1, args.decode_steps)):
        out = eng.decode_step()
    jax.block_until_ready(eng._cache_k)
    del out
    path = os.path.join(tempfile.mkdtemp(prefix="mxnet_tpu_decode_"),
                        "decode_trace.json")
    tracing.export_trace(path)
    hot = rank_trace_ops(path)
    B, D, V = eng.slots, cfg["d_model"], cfg["vocab"]
    H, S = cfg["n_heads"], eng.cache_len
    # decode's dominant GEMM operand shapes: the (slots x D) token-step
    # projections/FFN/head, and the (slots*heads x ring) attention
    # score/value rows the softmax·V fusion would act on
    shapes = [(B, D), (B, 4 * D), (B, V), (B * H, S)]
    meta = {"model": {k: cfg[k] for k in ("vocab", "d_model", "n_heads",
                                          "n_layers")},
            "slots": B, "cache_len": S, "steps": args.decode_steps,
            "shapes": [list(s) for s in shapes],
            "trace": path,
            "hot_ops": [{"name": n, "total_ms": round(ms, 3), "calls": c,
                         "est_hbm_bytes": est}
                        for n, ms, c, est in hot]}
    return meta, hot, shapes


def run_migrate(path, max_age_days):
    """Rewrite a pre-dtype (legacy) table in place: every key gains the
    f32 tag its measurements were taken under, then the migrated table
    is re-validated."""
    from mxnet_tpu import fusion_cost as fc

    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError) as e:
        print("%s: cannot read (%s)" % (path, e), file=sys.stderr)
        return 1
    data, n = fc.migrate_legacy_table(data)
    data.setdefault("dtype_policy", "f32")
    fc.save_table(path, data)
    log("migrated %d legacy key(s) in %s (assumed f32)" % (n, path))
    return run_check(path, max_age_days)


def run_tune(args):
    import mxnet_tpu  # noqa: F401  (backend init)
    import jax

    from mxnet_tpu import dtype_policy as dtp
    from mxnet_tpu import fusion_cost as fc
    from mxnet_tpu.symbol import fusion as F

    # measurement precision (--dtype-policy): operands bound in the
    # policy's compute dtype, the policy tag stamped into the table
    # meta, and every emitted key carrying the dtype tag — bf16
    # measurements never reuse (or pollute) f32 entries
    policy = dtp.resolve_policy(args.dtype_policy)
    bench_dtype = str(policy.compute_dtype) if policy is not None         else "float32"

    hot = None
    if args.trace:
        hot = rank_trace_ops(args.trace)
        log("timeline ranking from %s (total ms | calls | est HBM bytes):"
            % args.trace)
        for name, ms, n, est in hot:
            log("  %-40s %10.3f %6d %s"
                % (name, ms, n, "%12.0f" % est if est else "           -"))

    lm_shapes = []
    lm_meta = None
    if args.lm:
        lm_meta, lm_hot, lm_shapes = profile_lm(args)
        log("LM timeline ranking (total ms | calls | est HBM bytes):")
        for name, ms, n, est in lm_hot:
            log("  %-40s %10.3f %6d %s"
                % (name, ms, n, "%12.0f" % est if est else "           -"))
    decode_meta = None
    if args.decode:
        decode_meta, dec_hot, dec_shapes = profile_decode(args)
        log("decode timeline ranking (total ms | calls | est HBM "
            "bytes):")
        for name, ms, n, est in dec_hot:
            log("  %-40s %10.3f %6d %s"
                % (name, ms, n, "%12.0f" % est if est else "           -"))
        for s in dec_shapes:
            if s not in lm_shapes:
                lm_shapes.append(s)

    names = ([p for p in args.patterns.split(",") if p]
             if args.patterns else F.list_patterns())
    shapes = None
    if args.shapes:
        shapes = [tuple(int(d) for d in s.lower().split("x"))
                  for s in args.shapes]

    table = fc.CostTable(meta={
        "version": fc.TABLE_VERSION,
        "backend": jax.default_backend(),
        "devices": [str(d) for d in jax.devices()],
        "jax": jax.__version__,
        "created": __import__("datetime").datetime.now(
            __import__("datetime").timezone.utc).isoformat(
                timespec="seconds"),
        "iters": args.iters,
        "dtype_policy": dtp.policy_tag(policy),
        "bench_dtype": bench_dtype,
    })
    if hot:
        table.meta["trace_hot_ops"] = [
            {"name": n, "total_ms": round(ms, 3), "calls": c,
             "est_hbm_bytes": est} for n, ms, c, est in hot]
    if lm_meta is not None:
        table.meta["lm_profile"] = lm_meta
    if decode_meta is not None:
        table.meta["decode_profile"] = decode_meta

    for name in names:
        pattern = F.get_pattern(name)
        if pattern.bench_builder is None:
            log("skip %s: no bench_builder" % name)
            continue
        pattern_shapes = list(shapes or pattern.bench_shapes)
        # the LM's rank-2 GEMM shapes ride along only where the
        # pattern's own bench chain is rank-2 (matmul/elementwise);
        # conv patterns expect NCHW and would just trace-and-skip
        if all(len(s) == 2 for s in pattern.bench_shapes):
            for s in lm_shapes:
                if s not in pattern_shapes:
                    pattern_shapes.append(s)
        for shape in pattern_shapes:
            if len(shape) < 2:
                log("skip %s @ %s: chain needs >=2 dims" % (name, shape))
                continue
            try:
                res = F.microbench(name, shape, iters=args.iters,
                                   grad=not args.no_grad,
                                   dtype=bench_dtype)
            except Exception as e:
                log("skip %s @ %s: %s" % (name, shape, e))
                continue
            if not res["fired"]:
                log("WARNING: pattern %s did not match its own bench "
                    "chain at %s" % (name, shape))
                continue
            extra = {"shape": list(shape),
                     "fused_fwd_ms": round(res["fused_fwd_ms"], 6),
                     "unfused_fwd_ms": round(res["unfused_fwd_ms"], 6),
                     "speedup_infer": round(res["speedup_infer"], 4)}
            fused = res.get("fused_train_ms", res["fused_fwd_ms"])
            unfused = res.get("unfused_train_ms", res["unfused_fwd_ms"])
            e = table.add(res["key"], fused, unfused, **extra)
            log("%-48s fused %8.3f ms  unfused %8.3f ms  speedup %.2fx"
                % (res["key"], fused, unfused, e["speedup"]))

    fc.save_table(args.out, table)
    fires = sum(1 for e in table.entries.values()
                if e["speedup"] >= fc.SPEEDUP_FIRE)
    slower = sum(1 for e in table.entries.values()
                 if e["speedup"] < fc.SPEEDUP_KEEP)
    log("wrote %s: %d entries (%d fire >=%.2fx, %d measured slower -> "
        "suppressed)" % (args.out, len(table.entries), fires,
                         fc.SPEEDUP_FIRE, slower))
    log("activate with MXNET_FUSION_TUNE=%s (or "
        "mxnet_tpu.config.fusion_cost_table(%r))" % (args.out, args.out))
    return 0


def main(argv=None):
    p = argparse.ArgumentParser(
        description="Measure fused-vs-unfused per shape and write the "
                    "fusion cost table (or --check an existing one)")
    p.add_argument("--out", help="cost-table JSON to write (tuning mode)")
    p.add_argument("--check", metavar="TABLE",
                   help="validate a cost-table JSON instead of tuning")
    p.add_argument("--migrate", metavar="TABLE",
                   help="rewrite a pre-dtype (legacy) table in place: "
                        "keys gain the f32 tag, then the table is "
                        "re-validated")
    p.add_argument("--dtype-policy", default=None,
                   help="measure under this dtype policy's compute "
                        "dtype (f32/bf16_mixed/bf16_pure; default: "
                        "MXNET_DTYPE_POLICY) and stamp the tag into "
                        "the table meta")
    p.add_argument("--trace", help="chrome-trace export to rank hot ops "
                                   "from (tracing.export_trace output)")
    p.add_argument("--lm", action="store_true",
                   help="profile the transformer-LM bench model "
                        "(tools/bench_lm.py) live and fold its hot-op "
                        "ranking + matmul/attention operand shapes into "
                        "the tuning run")
    p.add_argument("--lm-steps", type=int, default=2,
                   help="--lm: traced LM steps (default 2)")
    p.add_argument("--lm-mesh", default=None,
                   help="--lm: mesh spec for the profiled LM trainer "
                        "(default: MXNET_MESH, else single device)")
    p.add_argument("--decode", action="store_true",
                   help="profile the KV-cache decode engine "
                        "(mxnet_tpu/generate.py via tools/"
                        "bench_decode.py's model) live and fold its "
                        "small-batch, cache-length-keyed hot shapes "
                        "into the tuning run — the shapes token decode "
                        "actually runs")
    p.add_argument("--decode-steps", type=int, default=4,
                   help="--decode: traced decode steps (default 4)")
    p.add_argument("--decode-slots", type=int, default=8,
                   help="--decode: engine decode slots (default 8)")
    p.add_argument("--decode-cache-len", type=int, default=128,
                   help="--decode: KV ring length profiled (default "
                        "128)")
    p.add_argument("--patterns", help="comma list (default: all "
                                      "registered)")
    p.add_argument("--shapes", nargs="*",
                   help="shapes like 64x1024 (default: per-pattern "
                        "bench_shapes)")
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--no-grad", action="store_true",
                   help="time forward only (serving-shaped tables)")
    p.add_argument("--max-age-days", type=float, default=90.0,
                   help="--check: flag entries older than this")
    args = p.parse_args(argv)
    if args.check:
        return run_check(args.check, args.max_age_days)
    if args.migrate:
        return run_migrate(args.migrate, args.max_age_days)
    if not args.out:
        p.error("--out is required in tuning mode (or use --check)")
    return run_tune(args)


if __name__ == "__main__":
    sys.exit(main())
