"""Benchmark: LM generation — KV-cache decode vs full re-forward.

The framework's first LATENCY-bound hot path (ISSUE 13): where
``bench_lm.py`` measures train tokens/s and MFU, this bench measures
the serving side of the same transformer LM through
``mxnet_tpu/generate.py`` — tokens/s/user, time-to-first-token
p50/p99, KV-cache occupancy, and the continuous-batching batch-size
profile — against the no-cache baseline that re-runs the full context
for every token (what decode costs without the engine).

Two measured phases after warmup, both jit-compiled (the comparison is
the algorithm, not eager dispatch overhead):

1. **Baseline**: one fixed-shape full-context forward per generated
   token (compiled once at ``--ctx``), greedy next-token on the host.
2. **Engine**: ``GenerationEngine`` + ``TokenServer`` serving
   ``--users`` concurrent prompts with the KV-cache decode step; plus
   a single-user pass for the apples-to-apples per-sequence rate.

Emits TWO ``BENCH {json}`` records through the perf ledger (the
``lm_decode`` record kind): ``lm_decode_tokens_per_sec_per_user``
(tokens/sec/user, higher-better) and ``lm_decode_ttft_p99_ms`` (ms,
LOWER-better — ``tools/perf_gate.py`` gates latency units upward).
``cache_speedup`` carries the acceptance number: aggregate KV-cache
tokens/s over the re-forward baseline (>= 3x on CPU at ctx 256).

    # CPU smoke (the committed numbers):
    python tools/bench_decode.py

    # real chip:
    python tools/bench_decode.py --users 16 --ctx 512

Progress goes to stderr; stdout is the marked record lines only.
"""
import argparse
import os
import sys
import time

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
for p in (REPO, os.path.join(REPO, "examples")):
    if p not in sys.path:
        sys.path.insert(0, p)

_T0 = time.time()


def log(msg):
    print("[bench_decode %6.1fs] %s" % (time.time() - _T0, msg),
          file=sys.stderr, flush=True)


# canonical canned result for the schema-guard tests (tests/
# test_generate.py and tests/test_perf_observatory.py import THIS so
# the two guards can never drift apart)
CANNED_RESULT = {
    "metric": "lm_decode_tokens_per_sec_per_user", "value": 225.1,
    "unit": "tokens/sec/user", "tokens_per_sec": 1801.0,
    "tokens_per_sec_single_user": 246.9,
    "baseline_tokens_per_sec": 163.1, "cache_speedup": 11.0,
    "ttft_ms": {"p50": 10.3, "p99": 19.8}, "cache_occupancy": 0.24,
    "batch_tokens_mean": 8.0, "users": 8, "slots": 8, "cache_len": 256,
    "buckets": [32, 64, 128, 256], "ctx": 256, "prompt_len": 16,
    "gen_tokens": 48, "sampling": "greedy", "dtype_policy": "f32",
    "mesh_shape": {}, "layout": None, "devices": 1,
}


def ledger_records(result):
    """perf_ledger records for one bench_decode run: the ``lm_decode``
    record kind — a tokens/sec/user throughput row and a TTFT p99
    latency row (lower-better by unit), topology/precision stamping
    provenance.  The tier-1 schema guard calls this with a canned
    result."""
    from mxnet_tpu import perf_ledger

    prov = {"mesh_shape": result.get("mesh_shape"),
            "layout": result.get("layout"),
            "dtype_policy": result.get("dtype_policy")}
    fields = {k: v for k, v in result.items()
              if k not in ("metric", "value", "unit")}
    recs = [perf_ledger.make_record(
        result["metric"], result["value"], result["unit"], prov=prov,
        **fields)]
    ttft = result.get("ttft_ms") or {}
    if ttft.get("p99") is not None:
        recs.append(perf_ledger.make_record(
            "lm_decode_ttft_p99_ms", ttft["p99"], "ms", prov=prov,
            ttft_p50_ms=ttft.get("p50"), users=result.get("users"),
            slots=result.get("slots"),
            prompt_len=result.get("prompt_len")))
    return recs


def build_lm(vocab=None, d_model=None, n_heads=None, n_layers=None,
             max_len=256):
    """The decode benchmark-of-record model: bench_lm's CPU-smoke /
    TPU defaults at inference shapes, shared with tests and
    ``tools/autotune.py --decode``."""
    import jax

    import mxnet_tpu as mx
    from transformer_lm import TransformerLM

    on_tpu = any(d.platform != "cpu" for d in jax.devices())
    vocab = vocab or (32000 if on_tpu else 256)
    d_model = d_model or (512 if on_tpu else 64)
    n_heads = n_heads or (8 if on_tpu else 4)
    n_layers = n_layers or (8 if on_tpu else 2)
    mx.random.seed(0)
    lm = TransformerLM(vocab_size=vocab, d_model=d_model,
                       n_heads=n_heads, n_layers=n_layers,
                       max_len=max_len)
    lm.initialize(mx.init.Xavier())
    cfg = dict(vocab=vocab, d_model=d_model, n_heads=n_heads,
               n_layers=n_layers, max_len=max_len, on_tpu=on_tpu)
    return lm, cfg


def make_full_forward(lm):
    """One jitted full-context forward over committed params — the
    no-cache re-forward baseline's compiled program."""
    import jax

    from mxnet_tpu.gluon import block as block_mod
    from mxnet_tpu.ndarray import NDArray

    params = list(lm.collect_params().values())
    arrays = tuple(jax.device_put(p.data()._data) for p in params)

    def forward(tokens, params_):
        with block_mod.swapped_params(params, params_):
            return lm(NDArray(tokens))._data

    return jax.jit(forward), arrays


def run_baseline(lm, ctx, prompt, gen_tokens):
    """Greedy generation by full-context re-forward at ONE compiled
    shape (1, ctx): the cost of decode without a KV cache."""
    fwd, arrays = make_full_forward(lm)
    toks = np.zeros((1, ctx), np.int32)
    n = prompt.size
    toks[0, :n] = prompt
    gen_tokens = min(gen_tokens, ctx - n)
    # warmup: the one compile
    np.asarray(fwd(toks, arrays))
    t0 = time.perf_counter()
    pos = n - 1
    for _ in range(gen_tokens):
        logits = np.asarray(fwd(toks, arrays))
        nxt = int(logits[0, pos].argmax())
        pos += 1
        toks[0, pos] = nxt
    dt = time.perf_counter() - t0
    log("[baseline] %d tokens by re-forward @ ctx %d in %.3fs "
        "(%.1f tok/s)" % (gen_tokens, ctx, dt, gen_tokens / dt))
    return gen_tokens / dt


def run(users=None, slots=None, ctx=256, prompt_len=16, gen_tokens=None,
        dtype_policy=None, mesh=None, layout=None, trace_out=None,
        baseline=True, **model_kw):
    import jax

    from mxnet_tpu import generate, telemetry, tracing

    telemetry.enable()
    if trace_out:
        tracing.enable()
        from mxnet_tpu import profiler

        profiler.set_config(aggregate_stats=True)
    lm, cfg = build_lm(max_len=ctx, **model_kw)
    if slots is None:
        slots = 16 if cfg["on_tpu"] else 8
    if users is None:
        users = slots
    if gen_tokens is None:
        gen_tokens = 128 if cfg["on_tpu"] else 48
    gen_tokens = min(gen_tokens, ctx - prompt_len)
    rng = np.random.RandomState(0)
    prompt = rng.randint(0, cfg["vocab"], prompt_len).astype(np.int32)

    if dtype_policy is None:
        dtype_policy = os.environ.get("BENCH_DTYPE_POLICY") or \
            ("bf16_mixed" if cfg["on_tpu"] else None)
    eng = generate.GenerationEngine(
        lm, slots=slots, cache_len=ctx, mesh=mesh, layout=layout,
        dtype_policy=dtype_policy,
        sampling=generate.SamplingConfig(greedy=True))
    log("engine: slots=%d cache_len=%d buckets=%s dtype=%s mesh=%s"
        % (eng.slots, eng.cache_len, eng.buckets, eng.dtype_policy_tag,
           eng.mesh_shape))

    baseline_tps = None
    if baseline:
        baseline_tps = run_baseline(lm, ctx, prompt, gen_tokens)

    srv = generate.TokenServer(eng, queue_depth=max(users, 4),
                               max_new_tokens=gen_tokens)
    # warmup: one short request compiles the prompt's prefill bucket +
    # the decode step (or loads them from the AOT store)
    srv.generate(prompt, max_new_tokens=2, timeout=600)
    telemetry.reset()

    # phase 1 — single user: the apples-to-apples per-sequence rate
    t0 = time.perf_counter()
    r1 = srv.generate(prompt, max_new_tokens=gen_tokens, timeout=600)
    dt1 = time.perf_counter() - t0
    single_tps = len(r1.tokens) / dt1
    log("[engine 1 user] %d tokens in %.3fs (%.1f tok/s)"
        % (len(r1.tokens), dt1, single_tps))

    # phase 2 — continuous batching at --users concurrency
    telemetry.reset()
    t0 = time.perf_counter()
    futs = [srv.submit(prompt, block=True, timeout=600)
            for _ in range(users)]
    # peak cache occupancy, polled while the batch decodes (admissions
    # land on the worker thread after submit returns)
    occ_peak = 0.0
    while not all(f.done() for f in futs):
        occ_peak = max(occ_peak, eng.occupancy()["occupancy"])
        time.sleep(0.002)
    results = [f.result(timeout=600) for f in futs]
    dt = time.perf_counter() - t0
    total = sum(len(r.tokens) for r in results)
    agg_tps = total / dt
    per_user = agg_tps / users
    ttfts = sorted(r.ttft_s for r in results)
    p50 = float(np.percentile(ttfts, 50)) * 1e3
    p99 = float(np.percentile(ttfts, 99)) * 1e3
    bt_count = telemetry.DECODE_BATCH_TOKENS.count()
    bt_mean = (telemetry.DECODE_BATCH_TOKENS.sum() / bt_count) \
        if bt_count else None
    srv.close()
    log("[engine %d users] %d tokens in %.3fs (%.1f tok/s aggregate, "
        "%.1f tok/s/user, TTFT p50 %.1f ms p99 %.1f ms)"
        % (users, total, dt, agg_tps, per_user, p50, p99))

    result = {
        "metric": "lm_decode_tokens_per_sec_per_user",
        "value": round(per_user, 2),
        "unit": "tokens/sec/user",
        "tokens_per_sec": round(agg_tps, 2),
        "tokens_per_sec_single_user": round(single_tps, 2),
        "baseline_tokens_per_sec": round(baseline_tps, 2)
        if baseline_tps else None,
        "cache_speedup": round(agg_tps / baseline_tps, 2)
        if baseline_tps else None,
        "ttft_ms": {"p50": round(p50, 2), "p99": round(p99, 2)},
        "cache_occupancy": round(occ_peak, 4),
        "batch_tokens_mean": round(bt_mean, 2)
        if bt_mean is not None else None,
        "users": users,
        "slots": eng.slots,
        "cache_len": eng.cache_len,
        "buckets": eng.buckets,
        "ctx": ctx,
        "prompt_len": prompt_len,
        "gen_tokens": gen_tokens,
        "sampling": eng.sampling.tag,
        "dtype_policy": eng.dtype_policy_tag,
        "mesh_shape": eng.mesh_shape,
        "layout": eng.layout_name,
        "devices": len(jax.devices()),
    }
    if baseline_tps:
        log("cache speedup vs re-forward @ ctx %d: %.2fx (aggregate), "
            "%.2fx (single user)" % (ctx, agg_tps / baseline_tps,
                                     single_tps / baseline_tps))
    if trace_out:
        from mxnet_tpu import tracing as _tr

        _tr.export_trace(trace_out)
        log("unified trace written to %s" % trace_out)
    return result


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--users", type=int, default=None,
                   help="concurrent generation requests (default: "
                        "= slots)")
    p.add_argument("--slots", type=int, default=None,
                   help="decode slots / KV-cache lanes (default 8 CPU, "
                        "16 TPU)")
    p.add_argument("--ctx", type=int, default=256,
                   help="context window: cache ring length AND the "
                        "baseline's fixed re-forward shape (default "
                        "256 — the acceptance shape)")
    p.add_argument("--prompt-len", type=int, default=16)
    p.add_argument("--gen-tokens", type=int, default=None,
                   help="tokens generated per request (default 48 CPU, "
                        "128 TPU)")
    p.add_argument("--dtype-policy", default=None,
                   help="engine dtype policy (cache dtype follows its "
                        "compute dtype; default BENCH_DTYPE_POLICY, "
                        "else bf16_mixed on TPU)")
    p.add_argument("--mesh", default=None,
                   help="mesh spec for tp-sharded serving, e.g. "
                        "dp=1,tp=8 (default: MXNET_MESH)")
    p.add_argument("--layout", default=None)
    p.add_argument("--no-baseline", action="store_true",
                   help="skip the re-forward baseline phase")
    p.add_argument("--trace-out", default=None,
                   help="write the measured run's unified chrome trace "
                        "(tools/autotune.py --decode consumes it)")
    p.add_argument("--vocab", type=int, default=None)
    p.add_argument("--d-model", type=int, default=None)
    p.add_argument("--n-heads", type=int, default=None)
    p.add_argument("--n-layers", type=int, default=None)
    a = p.parse_args(argv)
    result = run(users=a.users, slots=a.slots, ctx=a.ctx,
                 prompt_len=a.prompt_len, gen_tokens=a.gen_tokens,
                 dtype_policy=a.dtype_policy, mesh=a.mesh,
                 layout=a.layout, trace_out=a.trace_out,
                 baseline=not a.no_baseline, vocab=a.vocab,
                 d_model=a.d_model, n_heads=a.n_heads,
                 n_layers=a.n_layers)
    from mxnet_tpu import perf_ledger

    for rec in ledger_records(result):
        perf_ledger.emit(rec)
    return 0


if __name__ == "__main__":
    sys.exit(main())
