"""Benchmark: LM generation — KV-cache decode vs full re-forward.

The framework's first LATENCY-bound hot path (ISSUE 13): where
``bench_lm.py`` measures train tokens/s and MFU, this bench measures
the serving side of the same transformer LM through
``mxnet_tpu/generate.py`` — tokens/s/user, time-to-first-token
p50/p99, KV-cache occupancy, and the continuous-batching batch-size
profile — against the no-cache baseline that re-runs the full context
for every token (what decode costs without the engine).

Two measured phases after warmup, both jit-compiled (the comparison is
the algorithm, not eager dispatch overhead):

1. **Baseline**: one fixed-shape full-context forward per generated
   token (compiled once at ``--ctx``), greedy next-token on the host.
2. **Engine**: ``GenerationEngine`` + ``TokenServer`` serving
   ``--users`` concurrent prompts with the KV-cache decode step; plus
   a single-user pass for the apples-to-apples per-sequence rate.

Emits TWO ``BENCH {json}`` records through the perf ledger (the
``lm_decode`` record kind): ``lm_decode_tokens_per_sec_per_user``
(tokens/sec/user, higher-better) and ``lm_decode_ttft_p99_ms`` (ms,
LOWER-better — ``tools/perf_gate.py`` gates latency units upward).
``cache_speedup`` carries the acceptance number: aggregate KV-cache
tokens/s over the re-forward baseline (>= 3x on CPU at ctx 256).

    # CPU smoke (the committed numbers):
    python tools/bench_decode.py

    # real chip:
    python tools/bench_decode.py --users 16 --ctx 512

Paged-engine modes (ISSUE 16) measure each serving lever behind its
own perf-ledger metric so ``tools/perf_gate.py`` can gate them
independently:

* ``--paged`` — the default two-phase bench on the
  :class:`PagedGenerationEngine` (block KV pool, sharing/spec off):
  ``lm_decode_paged_tokens_per_sec_per_user``.
* ``--prefix-share`` — N users behind ONE system prompt, aggregate
  tokens/s with copy-on-write prefix sharing vs the same engine with
  sharing disabled: ``lm_decode_prefix_share_tokens_per_sec`` (up) and
  ``lm_decode_prefix_hit_rate`` (ratio, up).
* ``--chunked-prefill`` — short-prompt TTFT p99 while long prompts
  prefill in fixed chunks interleaved with decode, vs monolithic
  single-chunk prefill: ``lm_decode_ttft_interference_p99_ms`` (ms,
  LOWER-better).
* ``--spec`` — n-gram self-speculative decoding on a repetitive
  prompt, drafted-and-accepted tokens per verify step plus the
  wall-clock speedup over the same engine without drafting:
  ``lm_decode_spec_accepted_per_step`` (tokens/step, up).

Progress goes to stderr; stdout is the marked record lines only.
"""
import argparse
import os
import sys
import time

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
for p in (REPO, os.path.join(REPO, "examples")):
    if p not in sys.path:
        sys.path.insert(0, p)

_T0 = time.time()


def log(msg):
    print("[bench_decode %6.1fs] %s" % (time.time() - _T0, msg),
          file=sys.stderr, flush=True)


# canonical canned result for the schema-guard tests (tests/
# test_generate.py and tests/test_perf_observatory.py import THIS so
# the two guards can never drift apart)
CANNED_RESULT = {
    "metric": "lm_decode_tokens_per_sec_per_user", "value": 225.1,
    "unit": "tokens/sec/user", "tokens_per_sec": 1801.0,
    "tokens_per_sec_single_user": 246.9,
    "baseline_tokens_per_sec": 163.1, "cache_speedup": 11.0,
    "ttft_ms": {"p50": 10.3, "p99": 19.8}, "cache_occupancy": 0.24,
    "batch_tokens_mean": 8.0, "users": 8, "slots": 8, "cache_len": 256,
    "buckets": [32, 64, 128, 256], "ctx": 256, "prompt_len": 16,
    "gen_tokens": 48, "sampling": "greedy", "dtype_policy": "f32",
    "mesh_shape": {}, "layout": None, "devices": 1,
}


# per-mode canned results: same contract as CANNED_RESULT — the
# schema guard feeds each through ledger_records so a field rename in
# run_* shows up as a tier-1 failure, not a silently-reshaped record
CANNED_PAGED_RESULT = {
    "metric": "lm_decode_paged_tokens_per_sec_per_user", "value": 733.4,
    "unit": "tokens/sec/user", "tokens_per_sec": 5866.9,
    "tokens_per_sec_single_user": 1163.0,
    "baseline_tokens_per_sec": 199.0, "cache_speedup": 29.5,
    "ttft_ms": {"p50": 8.9, "p99": 15.7}, "cache_occupancy": 0.23,
    "batch_tokens_mean": 7.0, "users": 8, "slots": 8, "cache_len": 256,
    "buckets": None, "page_size": 16, "num_pages": 129,
    "pages_in_use_peak": 128, "prefill_chunk": 32, "ctx": 256,
    "prompt_len": 16, "gen_tokens": 48, "sampling": "greedy",
    "dtype_policy": "f32", "mesh_shape": {}, "layout": None,
    "devices": 1,
}

CANNED_PREFIX_SHARE_RESULT = {
    "metric": "lm_decode_prefix_share_tokens_per_sec", "value": 18774.9,
    "unit": "tokens/sec", "noshare_tokens_per_sec": 16223.5,
    "prefix_speedup": 1.16, "prefix_hit_rate": 0.5,
    "prefix_hit_tokens_per_user": 112, "system_len": 112, "tail_len": 8,
    "users": 8, "slots": 4, "page_size": 16, "cache_len": 256,
    "gen_tokens": 32, "sampling": "greedy", "dtype_policy": "f32",
    "mesh_shape": {}, "layout": None, "devices": 1,
}

CANNED_CHUNKED_PREFILL_RESULT = {
    "metric": "lm_decode_ttft_interference_p99_ms", "value": 5.73,
    "unit": "ms", "ttft_interference_p50_ms": 2.09,
    "monolithic_ttft_p99_ms": 26.91, "interference_ratio": 4.7,
    "prefill_chunk": 16, "long_prompt_len": 160, "short_prompt_len": 8,
    "foreground_requests": 6, "background_users": 2, "slots": 4,
    "page_size": 16, "cache_len": 256, "sampling": "greedy",
    "dtype_policy": "f32", "mesh_shape": {}, "layout": None,
    "devices": 1,
}

CANNED_SPEC_RESULT = {
    "metric": "lm_decode_spec_accepted_per_step", "value": 0.6667,
    "unit": "tokens/step", "spec_accept_rate": 0.2235,
    "spec_tokens_per_sec": 1790.5, "nospec_tokens_per_sec": 2156.0,
    "spec_speedup": 0.83, "spec_k": 4, "spec_ngram": 3, "slots": 2,
    "page_size": 16, "cache_len": 256, "prompt_len": 24,
    "gen_tokens": 160, "sampling": "greedy", "dtype_policy": "f32",
    "mesh_shape": {}, "layout": None, "devices": 1,
}

# mode name -> canned result (tests iterate this to guard every mode)
CANNED_MODE_RESULTS = {
    "ring": CANNED_RESULT,
    "paged": CANNED_PAGED_RESULT,
    "prefix_share": CANNED_PREFIX_SHARE_RESULT,
    "chunked_prefill": CANNED_CHUNKED_PREFILL_RESULT,
    "spec": CANNED_SPEC_RESULT,
}


def ledger_records(result):
    """perf_ledger records for one bench_decode run: the ``lm_decode``
    record kind — the mode's headline metric plus its companion rows
    (TTFT p99 for the throughput modes, the prefix hit-rate ratio for
    ``--prefix-share``), topology/precision stamping provenance.  The
    tier-1 schema guard calls this with the canned results."""
    from mxnet_tpu import perf_ledger

    prov = {"mesh_shape": result.get("mesh_shape"),
            "layout": result.get("layout"),
            "dtype_policy": result.get("dtype_policy")}
    fields = {k: v for k, v in result.items()
              if k not in ("metric", "value", "unit")}
    recs = [perf_ledger.make_record(
        result["metric"], result["value"], result["unit"], prov=prov,
        **fields)]
    ttft = result.get("ttft_ms") or {}
    if ttft.get("p99") is not None:
        recs.append(perf_ledger.make_record(
            "lm_decode_ttft_p99_ms", ttft["p99"], "ms", prov=prov,
            ttft_p50_ms=ttft.get("p50"), users=result.get("users"),
            slots=result.get("slots"),
            prompt_len=result.get("prompt_len")))
    if result.get("prefix_hit_rate") is not None:
        recs.append(perf_ledger.make_record(
            "lm_decode_prefix_hit_rate", result["prefix_hit_rate"],
            "ratio", prov=prov, users=result.get("users"),
            system_len=result.get("system_len"),
            page_size=result.get("page_size")))
    return recs


def build_lm(vocab=None, d_model=None, n_heads=None, n_layers=None,
             max_len=256):
    """The decode benchmark-of-record model: bench_lm's CPU-smoke /
    TPU defaults at inference shapes, shared with tests and
    ``tools/autotune.py --decode``."""
    import jax

    import mxnet_tpu as mx
    from transformer_lm import TransformerLM

    on_tpu = any(d.platform != "cpu" for d in jax.devices())
    vocab = vocab or (32000 if on_tpu else 256)
    d_model = d_model or (512 if on_tpu else 64)
    n_heads = n_heads or (8 if on_tpu else 4)
    n_layers = n_layers or (8 if on_tpu else 2)
    mx.random.seed(0)
    lm = TransformerLM(vocab_size=vocab, d_model=d_model,
                       n_heads=n_heads, n_layers=n_layers,
                       max_len=max_len)
    lm.initialize(mx.init.Xavier())
    cfg = dict(vocab=vocab, d_model=d_model, n_heads=n_heads,
               n_layers=n_layers, max_len=max_len, on_tpu=on_tpu)
    return lm, cfg


def make_full_forward(lm):
    """One jitted full-context forward over committed params — the
    no-cache re-forward baseline's compiled program."""
    import jax

    from mxnet_tpu.gluon import block as block_mod
    from mxnet_tpu.ndarray import NDArray

    params = list(lm.collect_params().values())
    arrays = tuple(jax.device_put(p.data()._data) for p in params)

    def forward(tokens, params_):
        with block_mod.swapped_params(params, params_):
            return lm(NDArray(tokens))._data

    return jax.jit(forward), arrays


def run_baseline(lm, ctx, prompt, gen_tokens):
    """Greedy generation by full-context re-forward at ONE compiled
    shape (1, ctx): the cost of decode without a KV cache."""
    fwd, arrays = make_full_forward(lm)
    toks = np.zeros((1, ctx), np.int32)
    n = prompt.size
    toks[0, :n] = prompt
    gen_tokens = min(gen_tokens, ctx - n)
    # warmup: the one compile
    np.asarray(fwd(toks, arrays))
    t0 = time.perf_counter()
    pos = n - 1
    for _ in range(gen_tokens):
        logits = np.asarray(fwd(toks, arrays))
        nxt = int(logits[0, pos].argmax())
        pos += 1
        toks[0, pos] = nxt
    dt = time.perf_counter() - t0
    log("[baseline] %d tokens by re-forward @ ctx %d in %.3fs "
        "(%.1f tok/s)" % (gen_tokens, ctx, dt, gen_tokens / dt))
    return gen_tokens / dt


def run(users=None, slots=None, ctx=256, prompt_len=16, gen_tokens=None,
        dtype_policy=None, mesh=None, layout=None, trace_out=None,
        baseline=True, paged=None, page_size=None, prefill_chunk=None,
        **model_kw):
    import jax

    from mxnet_tpu import config, generate, telemetry, tracing

    telemetry.enable()
    if trace_out:
        tracing.enable()
        from mxnet_tpu import profiler

        profiler.set_config(aggregate_stats=True)
    lm, cfg = build_lm(max_len=ctx, **model_kw)
    if slots is None:
        slots = 16 if cfg["on_tpu"] else 8
    if users is None:
        users = slots
    if gen_tokens is None:
        gen_tokens = 128 if cfg["on_tpu"] else 48
    gen_tokens = min(gen_tokens, ctx - prompt_len)
    rng = np.random.RandomState(0)
    prompt = rng.randint(0, cfg["vocab"], prompt_len).astype(np.int32)

    if dtype_policy is None:
        dtype_policy = os.environ.get("BENCH_DTYPE_POLICY") or \
            ("bf16_mixed" if cfg["on_tpu"] else None)
    if paged is None:
        paged = bool(config.get("MXNET_DECODE_PAGED"))
    if paged:
        # the isolated paged-layout measurement: sharing and drafting
        # off so the number moves only with the page pool mechanics
        eng = generate.PagedGenerationEngine(
            lm, slots=slots, cache_len=ctx, page_size=page_size,
            prefill_chunk=prefill_chunk, spec_k=0, prefix_share=False,
            mesh=mesh, layout=layout, dtype_policy=dtype_policy,
            sampling=generate.SamplingConfig(greedy=True))
        log("engine: paged slots=%d cache_len=%d page=%d pages=%d "
            "chunk=%d dtype=%s mesh=%s"
            % (eng.slots, eng.cache_len, eng.page_size, eng.num_pages,
               eng.prefill_chunk, eng.dtype_policy_tag, eng.mesh_shape))
    else:
        eng = generate.GenerationEngine(
            lm, slots=slots, cache_len=ctx, mesh=mesh, layout=layout,
            dtype_policy=dtype_policy,
            sampling=generate.SamplingConfig(greedy=True))
        log("engine: slots=%d cache_len=%d buckets=%s dtype=%s mesh=%s"
            % (eng.slots, eng.cache_len, eng.buckets,
               eng.dtype_policy_tag, eng.mesh_shape))

    baseline_tps = None
    if baseline:
        baseline_tps = run_baseline(lm, ctx, prompt, gen_tokens)

    srv = generate.TokenServer(eng, queue_depth=max(users, 4),
                               max_new_tokens=gen_tokens)
    # warmup: one short request compiles the prompt's prefill bucket +
    # the decode step (or loads them from the AOT store)
    srv.generate(prompt, max_new_tokens=2, timeout=600)
    telemetry.reset()

    # phase 1 — single user: the apples-to-apples per-sequence rate
    t0 = time.perf_counter()
    r1 = srv.generate(prompt, max_new_tokens=gen_tokens, timeout=600)
    dt1 = time.perf_counter() - t0
    single_tps = len(r1.tokens) / dt1
    log("[engine 1 user] %d tokens in %.3fs (%.1f tok/s)"
        % (len(r1.tokens), dt1, single_tps))

    # phase 2 — continuous batching at --users concurrency
    telemetry.reset()
    t0 = time.perf_counter()
    futs = [srv.submit(prompt, block=True, timeout=600)
            for _ in range(users)]
    # peak cache occupancy, polled while the batch decodes (admissions
    # land on the worker thread after submit returns)
    occ_peak = 0.0
    pages_peak = 0
    while not all(f.done() for f in futs):
        occ = eng.occupancy()
        occ_peak = max(occ_peak, occ["occupancy"])
        pages_peak = max(pages_peak, occ.get("pages_in_use", 0))
        time.sleep(0.002)
    results = [f.result(timeout=600) for f in futs]
    dt = time.perf_counter() - t0
    total = sum(len(r.tokens) for r in results)
    agg_tps = total / dt
    per_user = agg_tps / users
    ttfts = sorted(r.ttft_s for r in results)
    p50 = float(np.percentile(ttfts, 50)) * 1e3
    p99 = float(np.percentile(ttfts, 99)) * 1e3
    bt_count = telemetry.DECODE_BATCH_TOKENS.count()
    bt_mean = (telemetry.DECODE_BATCH_TOKENS.sum() / bt_count) \
        if bt_count else None
    srv.close()
    log("[engine %d users] %d tokens in %.3fs (%.1f tok/s aggregate, "
        "%.1f tok/s/user, TTFT p50 %.1f ms p99 %.1f ms)"
        % (users, total, dt, agg_tps, per_user, p50, p99))

    result = {
        "metric": "lm_decode_paged_tokens_per_sec_per_user" if paged
        else "lm_decode_tokens_per_sec_per_user",
        "value": round(per_user, 2),
        "unit": "tokens/sec/user",
        "tokens_per_sec": round(agg_tps, 2),
        "tokens_per_sec_single_user": round(single_tps, 2),
        "baseline_tokens_per_sec": round(baseline_tps, 2)
        if baseline_tps else None,
        "cache_speedup": round(agg_tps / baseline_tps, 2)
        if baseline_tps else None,
        "ttft_ms": {"p50": round(p50, 2), "p99": round(p99, 2)},
        "cache_occupancy": round(occ_peak, 4),
        "batch_tokens_mean": round(bt_mean, 2)
        if bt_mean is not None else None,
        "users": users,
        "slots": eng.slots,
        "cache_len": eng.cache_len,
        "buckets": getattr(eng, "buckets", None),
        "ctx": ctx,
        "prompt_len": prompt_len,
        "gen_tokens": gen_tokens,
        "sampling": eng.sampling.tag,
        "dtype_policy": eng.dtype_policy_tag,
        "mesh_shape": eng.mesh_shape,
        "layout": eng.layout_name,
        "devices": len(jax.devices()),
    }
    if paged:
        result.update(page_size=eng.page_size, num_pages=eng.num_pages,
                      pages_in_use_peak=pages_peak,
                      prefill_chunk=eng.prefill_chunk)
    if baseline_tps:
        log("cache speedup vs re-forward @ ctx %d: %.2fx (aggregate), "
            "%.2fx (single user)" % (ctx, agg_tps / baseline_tps,
                                     single_tps / baseline_tps))
    if trace_out:
        from mxnet_tpu import tracing as _tr

        _tr.export_trace(trace_out)
        log("unified trace written to %s" % trace_out)
    return result


def _paged_server(lm, gen_tokens, **eng_kw):
    """PagedGenerationEngine + TokenServer with one warmup request so
    timed phases never include the chunk/decode/verify compiles."""
    import numpy as _np

    from mxnet_tpu import generate

    eng = generate.PagedGenerationEngine(
        lm, sampling=generate.SamplingConfig(greedy=True), **eng_kw)
    srv = generate.TokenServer(eng, queue_depth=64,
                               max_new_tokens=gen_tokens)
    warm = _np.arange(2, dtype=_np.int32)
    srv.generate(warm, max_new_tokens=2, timeout=600)
    return eng, srv


def run_prefix_share(users=8, slots=None, ctx=256, system_len=112,
                     tail_len=8, gen_tokens=32, page_size=None,
                     dtype_policy=None, mesh=None, layout=None,
                     **model_kw):
    """--prefix-share: N users behind one system prompt.  Aggregate
    tokens/s (prompt + generated, since sharing's win is prefill work
    avoided) with copy-on-write sharing on vs the same engine with it
    off — the ISSUE's committed CPU aggregate-throughput win."""
    import jax

    from mxnet_tpu import telemetry

    telemetry.enable()
    lm, cfg = build_lm(max_len=ctx, **model_kw)
    if slots is None:
        slots = 8 if cfg["on_tpu"] else 4
    rng = np.random.RandomState(0)
    system = rng.randint(0, cfg["vocab"], system_len).astype(np.int32)
    prompts = [np.concatenate([system, rng.randint(
        0, cfg["vocab"], tail_len).astype(np.int32)])
        for _ in range(users)]
    gen_tokens = min(gen_tokens, ctx - system_len - tail_len)

    def phase(share):
        eng, srv = _paged_server(
            lm, gen_tokens, slots=slots, cache_len=ctx,
            page_size=page_size, spec_k=0, prefix_share=share,
            mesh=mesh, layout=layout, dtype_policy=dtype_policy)
        t0 = time.perf_counter()
        futs = [srv.submit(pr, block=True, timeout=600)
                for pr in prompts]
        results = [f.result(timeout=600) for f in futs]
        dt = time.perf_counter() - t0
        # prompt tokens count: sharing's saving is prefill compute, so
        # the aggregate rate must include the tokens being prefilled
        total = sum(len(pr) + len(r.tokens)
                    for pr, r in zip(prompts, results))
        hit = eng.prefix_hit_rate()
        srv.close()
        log("[prefix share=%s] %d users x (%d prompt + %d gen) in "
            "%.3fs (%.1f tok/s aggregate, hit_rate %s)"
            % (share, users, system_len + tail_len, gen_tokens, dt,
               total / dt, "%.3f" % hit if hit is not None else "n/a"))
        return total / dt, hit, eng

    share_tps, hit_rate, eng = phase(True)
    noshare_tps, _, _ = phase(False)
    log("prefix-share aggregate win: %.2fx" % (share_tps / noshare_tps))
    return {
        "metric": "lm_decode_prefix_share_tokens_per_sec",
        "value": round(share_tps, 2),
        "unit": "tokens/sec",
        "noshare_tokens_per_sec": round(noshare_tps, 2),
        "prefix_speedup": round(share_tps / noshare_tps, 2),
        "prefix_hit_rate": round(hit_rate, 4)
        if hit_rate is not None else None,
        "prefix_hit_tokens_per_user":
            system_len // eng.page_size * eng.page_size,
        "system_len": system_len,
        "tail_len": tail_len,
        "users": users,
        "slots": slots,
        "page_size": eng.page_size,
        "cache_len": eng.cache_len,
        "gen_tokens": gen_tokens,
        "sampling": eng.sampling.tag,
        "dtype_policy": eng.dtype_policy_tag,
        "mesh_shape": eng.mesh_shape,
        "layout": eng.layout_name,
        "devices": len(jax.devices()),
    }


def run_chunked_prefill(slots=None, ctx=256, prefill_chunk=16,
                        long_prompt=160, short_prompt=8, rounds=6,
                        page_size=None, dtype_policy=None, mesh=None,
                        layout=None, **model_kw):
    """--chunked-prefill: the scheduling latency win.  Two background
    users decode continuously; each round submits a LONG prompt and a
    short prompt together and measures the short request's TTFT.  With
    chunked prefill the short prompt's one chunk interleaves between
    the long prompt's chunks and the decode steps; the comparison run
    prefills monolithically (chunk = full capacity), so the short
    request waits out the whole long dispatch."""
    import jax

    from mxnet_tpu import telemetry

    telemetry.enable()
    lm, cfg = build_lm(max_len=ctx, **model_kw)
    if slots is None:
        slots = 4
    rng = np.random.RandomState(0)
    bg_prompt = rng.randint(0, cfg["vocab"], short_prompt) \
        .astype(np.int32)
    long_p = rng.randint(0, cfg["vocab"], long_prompt).astype(np.int32)
    short_p = rng.randint(0, cfg["vocab"], short_prompt) \
        .astype(np.int32)
    bg_gen = min(ctx - short_prompt - 1, 200)

    def phase(chunk):
        eng, srv = _paged_server(
            lm, bg_gen, slots=slots, cache_len=ctx, page_size=page_size,
            prefill_chunk=chunk, spec_k=0, prefix_share=False,
            mesh=mesh, layout=layout, dtype_policy=dtype_policy)
        bg = [srv.submit(bg_prompt, block=True, timeout=600)
              for _ in range(2)]
        ttfts = []
        for _ in range(rounds):
            fl = srv.submit(long_p, max_new_tokens=2, block=True,
                            timeout=600)
            fs = srv.submit(short_p, max_new_tokens=2, block=True,
                            timeout=600)
            rs = fs.result(timeout=600)
            fl.result(timeout=600)
            ttfts.append(rs.ttft_s)
        for f in bg:
            f.result(timeout=600)
        srv.close()
        p50 = float(np.percentile(ttfts, 50)) * 1e3
        p99 = float(np.percentile(ttfts, 99)) * 1e3
        log("[chunk=%d] short-prompt TTFT under long-prefill "
            "interference: p50 %.1f ms p99 %.1f ms over %d rounds"
            % (chunk, p50, p99, rounds))
        return p50, p99, eng

    p50, p99, eng = phase(prefill_chunk)
    # monolithic = one chunk spanning the whole capacity
    _, mono_p99, _ = phase(ctx)
    log("prefill-interference win: monolithic p99 %.1f ms vs chunked "
        "%.1f ms (%.2fx)" % (mono_p99, p99, mono_p99 / p99))
    return {
        "metric": "lm_decode_ttft_interference_p99_ms",
        "value": round(p99, 2),
        "unit": "ms",
        "ttft_interference_p50_ms": round(p50, 2),
        "monolithic_ttft_p99_ms": round(mono_p99, 2),
        "interference_ratio": round(mono_p99 / p99, 2),
        "prefill_chunk": prefill_chunk,
        "long_prompt_len": long_prompt,
        "short_prompt_len": short_prompt,
        "foreground_requests": rounds,
        "background_users": 2,
        "slots": slots,
        "page_size": eng.page_size,
        "cache_len": eng.cache_len,
        "sampling": eng.sampling.tag,
        "dtype_policy": eng.dtype_policy_tag,
        "mesh_shape": eng.mesh_shape,
        "layout": eng.layout_name,
        "devices": len(jax.devices()),
    }


def run_spec(slots=2, ctx=256, prompt_len=24, gen_tokens=160, spec_k=4,
             spec_ngram=3, page_size=None, dtype_policy=None,
             mesh=None, layout=None, **model_kw):
    """--spec: n-gram self-speculative decoding on a REPETITIVE prompt
    (a tiled pattern, the draft source's best case — real LM output
    loops similarly at small scale).  Accepted tokens per verify step
    plus the single-user wall-clock speedup over the same engine with
    drafting off.  Greedy, so the output is bit-identical either way —
    the bench asserts that too."""
    import jax

    from mxnet_tpu import telemetry

    telemetry.enable()
    lm, cfg = build_lm(max_len=ctx, **model_kw)
    rng = np.random.RandomState(0)
    base = rng.randint(0, cfg["vocab"], 6).astype(np.int32)
    prompt = np.tile(base, -(-prompt_len // 6))[:prompt_len]
    gen_tokens = min(gen_tokens, ctx - prompt_len - spec_k - 1)

    def phase(k):
        eng, srv = _paged_server(
            lm, gen_tokens, slots=slots, cache_len=ctx,
            page_size=page_size, spec_k=k, spec_ngram=spec_ngram,
            prefix_share=False, mesh=mesh, layout=layout,
            dtype_policy=dtype_policy)
        t0 = time.perf_counter()
        r = srv.generate(prompt, max_new_tokens=gen_tokens, timeout=600)
        dt = time.perf_counter() - t0
        aps = eng.spec_accepted_per_step()
        rate = eng.spec_accept_rate()
        srv.close()
        log("[spec_k=%d] %d tokens in %.3fs (%.1f tok/s, "
            "accepted/step %s, accept_rate %s)"
            % (k, len(r.tokens), dt, len(r.tokens) / dt,
               "%.2f" % aps if aps is not None else "n/a",
               "%.2f" % rate if rate is not None else "n/a"))
        return len(r.tokens) / dt, r.tokens, aps, rate, eng

    spec_tps, spec_toks, aps, rate, eng = phase(spec_k)
    nospec_tps, nospec_toks, _, _, _ = phase(0)
    if list(spec_toks) != list(nospec_toks):
        raise AssertionError(
            "speculative greedy decode diverged from the plain engine")
    log("spec speedup: %.2fx (greedy outputs identical)"
        % (spec_tps / nospec_tps))
    return {
        "metric": "lm_decode_spec_accepted_per_step",
        "value": round(aps, 4) if aps is not None else 0.0,
        "unit": "tokens/step",
        "spec_accept_rate": round(rate, 4)
        if rate is not None else None,
        "spec_tokens_per_sec": round(spec_tps, 2),
        "nospec_tokens_per_sec": round(nospec_tps, 2),
        "spec_speedup": round(spec_tps / nospec_tps, 2),
        "spec_k": spec_k,
        "spec_ngram": spec_ngram,
        "slots": slots,
        "page_size": eng.page_size,
        "cache_len": eng.cache_len,
        "prompt_len": prompt_len,
        "gen_tokens": gen_tokens,
        "sampling": eng.sampling.tag,
        "dtype_policy": eng.dtype_policy_tag,
        "mesh_shape": eng.mesh_shape,
        "layout": eng.layout_name,
        "devices": len(jax.devices()),
    }


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--users", type=int, default=None,
                   help="concurrent generation requests (default: "
                        "= slots)")
    p.add_argument("--slots", type=int, default=None,
                   help="decode slots / KV-cache lanes (default 8 CPU, "
                        "16 TPU)")
    p.add_argument("--ctx", type=int, default=256,
                   help="context window: cache ring length AND the "
                        "baseline's fixed re-forward shape (default "
                        "256 — the acceptance shape)")
    p.add_argument("--prompt-len", type=int, default=None,
                   help="prompt length (default 16; --spec 24, "
                        "--chunked-prefill's short prompt 8)")
    p.add_argument("--gen-tokens", type=int, default=None,
                   help="tokens generated per request (default 48 CPU, "
                        "128 TPU)")
    p.add_argument("--dtype-policy", default=None,
                   help="engine dtype policy (cache dtype follows its "
                        "compute dtype; default BENCH_DTYPE_POLICY, "
                        "else bf16_mixed on TPU)")
    p.add_argument("--mesh", default=None,
                   help="mesh spec for tp-sharded serving, e.g. "
                        "dp=1,tp=8 (default: MXNET_MESH)")
    p.add_argument("--layout", default=None)
    p.add_argument("--no-baseline", action="store_true",
                   help="skip the re-forward baseline phase")
    p.add_argument("--trace-out", default=None,
                   help="write the measured run's unified chrome trace "
                        "(tools/autotune.py --decode consumes it)")
    p.add_argument("--vocab", type=int, default=None)
    p.add_argument("--d-model", type=int, default=None)
    p.add_argument("--n-heads", type=int, default=None)
    p.add_argument("--n-layers", type=int, default=None)
    mode = p.add_mutually_exclusive_group()
    mode.add_argument("--paged", action="store_true",
                      help="run the two-phase bench on the paged "
                           "engine (sharing/spec off); also the "
                           "default when MXNET_DECODE_PAGED=1")
    mode.add_argument("--prefix-share", action="store_true",
                      help="N users behind one system prompt: "
                           "aggregate tokens/s, sharing on vs off")
    mode.add_argument("--chunked-prefill", action="store_true",
                      help="short-prompt TTFT p99 under long-prompt "
                           "prefill interference, chunked vs "
                           "monolithic")
    mode.add_argument("--spec", action="store_true",
                      help="n-gram speculative decoding: accepted "
                           "tokens per verify step + speedup vs "
                           "drafting off")
    p.add_argument("--page-size", type=int, default=None,
                   help="paged modes: positions per KV page (default "
                        "MXNET_DECODE_PAGE_SIZE)")
    p.add_argument("--prefill-chunk", type=int, default=None,
                   help="paged modes: prefill chunk length (default "
                        "MXNET_DECODE_PREFILL_CHUNK)")
    p.add_argument("--system-len", type=int, default=112,
                   help="--prefix-share: shared system-prompt length")
    p.add_argument("--spec-k", type=int, default=4,
                   help="--spec: draft tokens per verify step")
    p.add_argument("--spec-ngram", type=int, default=3,
                   help="--spec: n-gram match length for drafting")
    a = p.parse_args(argv)
    common = dict(dtype_policy=a.dtype_policy, mesh=a.mesh,
                  layout=a.layout, vocab=a.vocab, d_model=a.d_model,
                  n_heads=a.n_heads, n_layers=a.n_layers)
    if a.prefix_share:
        result = run_prefix_share(
            users=a.users or 8, slots=a.slots, ctx=a.ctx,
            system_len=a.system_len,
            gen_tokens=a.gen_tokens or 32, page_size=a.page_size,
            **common)
    elif a.chunked_prefill:
        result = run_chunked_prefill(
            slots=a.slots, ctx=a.ctx,
            prefill_chunk=a.prefill_chunk or 16,
            short_prompt=a.prompt_len or 8,
            page_size=a.page_size, **common)
    elif a.spec:
        result = run_spec(
            slots=a.slots or 2, ctx=a.ctx,
            prompt_len=a.prompt_len or 24,
            gen_tokens=a.gen_tokens or 160, spec_k=a.spec_k,
            spec_ngram=a.spec_ngram, page_size=a.page_size, **common)
    else:
        result = run(users=a.users, slots=a.slots, ctx=a.ctx,
                     prompt_len=a.prompt_len or 16,
                     gen_tokens=a.gen_tokens,
                     trace_out=a.trace_out,
                     baseline=not a.no_baseline,
                     paged=a.paged or None, page_size=a.page_size,
                     prefill_chunk=a.prefill_chunk, **common)
    from mxnet_tpu import perf_ledger

    for rec in ledger_records(result):
        perf_ledger.emit(rec)
    return 0


if __name__ == "__main__":
    sys.exit(main())
