"""ImageRecordIter throughput microbench.

Generates a synthetic ImageNet-like .rec (224x224 JPEGs) and measures
end-to-end pipeline throughput (read -> JPEG decode -> augment -> batch
-> device upload).  The number to beat is the training consumption rate
from bench.py (ResNet-50 img/s per chip): the pipeline must exceed it or
the chip starves.

Measured on this dev box (1 CPU core, TPU behind a ~150 ms/call
tunnel): host pipeline ~300-380 img/s *per core* (2.7 ms/img decode+
augment, JPEG q90 224px), end-to-end ~80 img/s limited entirely by the
tunnel's per-call latency.  Scaling model for a real TPU host: decode
scales linearly with preprocess_threads (PIL/numpy release the GIL), so
a standard 96-vCPU host sustains ~30k img/s host-side, and the uint8
upload (0.15 MB/img, PCIe >10 GB/s) adds <0.1 ms/img — comfortably above
the 2.1k img/s/chip ResNet-50 consumption rate from bench.py.

Usage: python tools/bench_io.py [n_images] [threads]
"""
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import recordio  # noqa: E402


def ledger_records(host_rate, e2e_rate, n_images, threads):
    """perf_ledger record(s) for one run: the host pipeline rate and
    the end-to-end (incl. device upload) rate — both must clear the
    training consumption rate or the chip starves.  The tier-1 schema
    guard calls this with canned rates."""
    from mxnet_tpu import perf_ledger

    fields = {"n_images": n_images, "threads": threads}
    return [
        perf_ledger.make_record("io_pipeline_host_img_s", host_rate,
                                "images/sec", **fields),
        perf_ledger.make_record("io_pipeline_e2e_img_s", e2e_rate,
                                "images/sec", **fields),
    ]


def make_rec(path, n, size=224):
    rng = np.random.RandomState(0)
    w = recordio.MXIndexedRecordIO(path + ".idx", path + ".rec", "w")
    t0 = time.time()
    for i in range(n):
        img = rng.randint(0, 255, (size, size, 3), np.uint8)
        w.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, float(i % 1000), i, 0), img, quality=90))
    w.close()
    print("wrote %d records in %.1fs" % (n, time.time() - t0))


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 2048
    threads = int(sys.argv[2]) if len(sys.argv) > 2 else os.cpu_count()
    with tempfile.TemporaryDirectory() as d:
        base = os.path.join(d, "synth")
        make_rec(base, n)
        it = mx.io.ImageRecordIter(
            path_imgrec=base + ".rec", path_imgidx=base + ".idx",
            batch_size=128, data_shape=(3, 224, 224), shuffle=True,
            rand_crop=True, rand_mirror=True, resize=256,
            mean_r=123.68, mean_g=116.78, mean_b=103.94,
            preprocess_threads=threads, prefetch_buffer=8)
        # warm epoch to populate thread-local readers + compile normalize
        for _ in it:
            pass
        it.reset()

        # (a) host pipeline rate: read -> decode -> augment -> batch,
        # futures drained without device work
        t0 = time.time()
        imgs = 0
        for _ in range(len(it._order) // 128):
            fut = it._pending.popleft()
            it._submit()
            data, _, pad = fut.result()
            imgs += data.shape[0] - pad
        host_rate = imgs / (time.time() - t0)
        print("host decode+augment+batch: %.0f img/s "
              "(%d imgs, %d threads, bs128)" % (host_rate, imgs, threads))

        # (b) end-to-end including uint8 device upload + fused
        # on-device normalize (blocks on the last batch only, like a
        # training consumer whose step consumes the previous upload)
        it.reset()
        t0 = time.time()
        imgs = 0
        last = None
        for batch in it:
            last = batch.data[0]
            imgs += batch.data[0].shape[0] - batch.pad
        last.asnumpy()  # drain the async queue
        e2e_rate = imgs / (time.time() - t0)
        print("end-to-end w/ device upload: %.0f img/s" % e2e_rate)

        from mxnet_tpu import perf_ledger

        for rec in ledger_records(round(host_rate, 1),
                                  round(e2e_rate, 1), n, threads):
            perf_ledger.emit(rec)


if __name__ == "__main__":
    main()
