"""Summarize (and sanity-check) mxnet_tpu Chrome-trace exports.

Reads the artifact written by ``mxnet_tpu.tracing.export_trace(path)``,
``mx.profiler.dump()``, or a flight-recorder bundle directory (the
bundle's ``trace.json`` is used), validates the Chrome-trace invariants
the tier-1 guard enforces (valid JSON, unique span IDs, resolvable
parents, ts-sorted events), and prints:

* per-span-name aggregates (count, total/mean/max ms, errors),
* per-device HBM watermarks from the counter track,
* with ``--tree``, the span hierarchy of the slowest roots,
* with ``--top-ops N``, the N most expensive op-timeline entries with
  total time and estimated HBM bytes (XLA cost analysis x call count) —
  the human-readable face of the ranking tools/autotune.py feeds on.

    python tools/trace_view.py trace.json [--top 20] [--tree]
    python tools/trace_view.py trace.json --top-ops 15
    python tools/trace_view.py flight_recorder/flight-...-nonfinite-p1-1
    python tools/trace_view.py part1.json part2.json   # split export
    python tools/trace_view.py --fleet SPOOL [--out pod.json]

Multiple paths validate TOGETHER: span parents resolve against the
union of span ids across all given files, so a parent exported into a
different file of the same capture (flight-recorder bundles split by
priority; fleet spools split by rank) is a resolvable reference, not a
silently-dropped "parent not in trace" violation — a parent id that
appears in NO given file still fails.

``--fleet`` treats the path as a fleet spool dir
(``mxnet_tpu/fleet.py``): per-rank chrome traces are stitched onto one
clock-offset-corrected pod timeline (pid = rank, span ids prefixed
``rN:``), torn snapshots are skipped with a counted warning, and the
stitched payload is validated and summarized like any trace
(``--out`` writes it for chrome://tracing / Perfetto).

Exit status is nonzero on malformed input or violated invariants, so CI
can gate on it.
"""
import argparse
import json
import os
import sys


def load_trace(path):
    """Trace payload from a file or a flight-recorder bundle dir."""
    if os.path.isdir(path):
        path = os.path.join(path, "trace.json")
    try:
        with open(path) as f:
            data = json.load(f)
    except OSError as e:
        raise SystemExit("%s: cannot read (%s)" % (path, e))
    except ValueError as e:
        raise SystemExit("%s: malformed JSON (%s)" % (path, e))
    if not isinstance(data, dict) or "traceEvents" not in data:
        raise SystemExit("%s: not a chrome trace (no 'traceEvents')"
                         % path)
    return data


def span_ids(data):
    """All span ids declared in a trace payload (for cross-file parent
    resolution when one capture was exported as several files)."""
    ids = set()
    for ev in data["traceEvents"]:
        if ev.get("ph") == "X" and ev.get("cat") == "span":
            sid = ev.get("args", {}).get("span_id")
            if sid is not None:
                ids.add(sid)
    return ids


def validate(data, known_span_ids=None):
    """Chrome-trace invariant check; returns a list of violations.

    ``known_span_ids`` extends parent resolution beyond this file: a
    parent living in a sibling file of the same capture resolves
    instead of being reported missing (the multi-file bundle case).
    Duplicate/ts/pid checks stay per-file."""
    problems = []
    seen_ids = set()
    last_ts = None
    for ev in data["traceEvents"]:
        ph = ev.get("ph")
        if ph == "M":
            continue  # metadata events carry no timestamp
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)):
            problems.append("event %r has no numeric ts" % (ev.get("name"),))
            continue
        if last_ts is not None and ts < last_ts:
            problems.append("ts not monotonic at %r (%s < %s)"
                            % (ev.get("name"), ts, last_ts))
        last_ts = ts
        if "pid" not in ev or "tid" not in ev:
            problems.append("event %r missing pid/tid" % (ev.get("name"),))
        if ph == "X" and ev.get("cat") == "span":
            sid = ev.get("args", {}).get("span_id")
            if sid is None:
                problems.append("span %r has no span_id" % (ev.get("name"),))
            elif sid in seen_ids:
                problems.append("duplicate span_id %s" % sid)
            else:
                seen_ids.add(sid)
    resolvable = seen_ids if known_span_ids is None \
        else (seen_ids | set(known_span_ids))
    for ev in data["traceEvents"]:
        if ev.get("ph") == "X" and ev.get("cat") == "span":
            parent = ev.get("args", {}).get("parent_id")
            if parent is not None and parent not in resolvable:
                problems.append("span %r parent %s not in trace"
                                % (ev.get("name"), parent))
    return problems


def _spans(data):
    return [ev for ev in data["traceEvents"]
            if ev.get("ph") == "X" and ev.get("cat") == "span"]


def summarize(data, top):
    spans = _spans(data)
    agg = {}  # name -> [count, total_us, max_us, errors]
    for ev in spans:
        st = agg.setdefault(ev["name"], [0, 0.0, 0.0, 0])
        st[0] += 1
        st[1] += ev.get("dur", 0.0)
        st[2] = max(st[2], ev.get("dur", 0.0))
        if ev.get("args", {}).get("status") == "error":
            st[3] += 1
    other = data.get("otherData", {})
    print("trace_id %s  pid %s  events %d  spans %d (open %s, dropped %s)"
          % (other.get("trace_id", "?"), other.get("pid", "?"),
             len(data["traceEvents"]), len(spans),
             other.get("open_spans", "?"), other.get("dropped_spans", "?")))
    if agg:
        print()
        print("%-36s %7s %11s %11s %11s %6s" % (
            "span", "count", "total(ms)", "mean(ms)", "max(ms)", "err"))
        for name, (n, tot, mx, err) in sorted(
                agg.items(), key=lambda kv: -kv[1][1])[:top]:
            print("%-36s %7d %11.3f %11.3f %11.3f %6d" % (
                name, n, tot / 1e3, tot / n / 1e3, mx / 1e3, err))
    mem = {}  # device -> (max in_use, max peak)
    for ev in data["traceEvents"]:
        if ev.get("ph") == "C":
            args = ev.get("args", {})
            dev = ev.get("name", "?")
            prev = mem.get(dev, (0, 0))
            mem[dev] = (max(prev[0], args.get("bytes_in_use", 0)),
                        max(prev[1], args.get("peak_bytes_in_use", 0)))
    if mem:
        print()
        print("%-44s %14s %14s" % ("memory counter", "max in_use",
                                   "max peak"))
        for dev, (in_use, peak) in sorted(mem.items()):
            print("%-44s %14d %14d" % (dev, in_use, peak))


def aggregate_op_costs(data):
    """``(name, total_ms, calls, est_hbm_bytes|None)`` rows over the op
    timeline, most expensive first.  est = per-program XLA 'bytes
    accessed' x call count; None when the program has no cost-analysis
    entry.  Profiler timeline ops only: span events cover their
    children and would double-count (and dominate) the ranking.  The
    single source of the ranking tools/autotune.py replays."""
    agg = {}  # name -> [total_us, count]
    for ev in data["traceEvents"]:
        if ev.get("ph") != "X" or ev.get("cat") != "op":
            continue
        st = agg.setdefault(ev.get("name", "?"), [0.0, 0])
        st[0] += ev.get("dur", 0.0)
        st[1] += 1
    costs = data.get("otherData", {}).get("xla_costs", {})
    rows = []
    for name, (tot_us, n) in agg.items():
        ba = costs.get(name, {}).get("bytes_accessed")
        est = ba * n if isinstance(ba, (int, float)) else None
        rows.append((name, tot_us / 1e3, n, est))
    rows.sort(key=lambda r: -r[1])
    return rows


def print_top_ops(data, n):
    """The N most expensive timeline ops: total/mean ms and estimated
    HBM bytes ('-' when the program has no cost-analysis entry)."""
    print()
    print("%-40s %7s %11s %11s %14s" % (
        "op/program", "calls", "total(ms)", "mean(ms)", "est HBM bytes"))
    for name, tot_ms, cnt, est in aggregate_op_costs(data)[:n]:
        est_s = "%14.0f" % est if est is not None else "%14s" % "-"
        print("%-40s %7d %11.3f %11.3f %s" % (
            name, cnt, tot_ms, tot_ms / cnt, est_s))


def print_tree(data, top):
    spans = _spans(data)
    by_id = {ev["args"]["span_id"]: ev for ev in spans
             if ev.get("args", {}).get("span_id")}
    children = {}
    roots = []
    for ev in spans:
        parent = ev.get("args", {}).get("parent_id")
        if parent and parent in by_id:
            children.setdefault(parent, []).append(ev)
        else:
            roots.append(ev)
    roots.sort(key=lambda e: -e.get("dur", 0.0))

    def walk(ev, depth):
        flags = "".join(
            [" !err" if ev["args"].get("status") == "error" else "",
             " (open)" if ev["args"].get("incomplete") else ""])
        print("%s%-*s %9.3f ms%s" % ("  " * depth, 40 - 2 * depth,
                                     ev["name"],
                                     ev.get("dur", 0.0) / 1e3, flags))
        for c in sorted(children.get(ev["args"].get("span_id"), []),
                        key=lambda e: e["ts"]):
            walk(c, depth + 1)

    print()
    for ev in roots[:top]:
        walk(ev, 0)


def print_bundle_events(path):
    """Wide-event window of a flight-recorder bundle (events.json,
    present since the events layer landed): outcome counts per kind +
    the writer's drop accounting — the per-request face of the crash.
    Silent when the bundle predates the events layer."""
    try:
        with open(path) as f:
            payload = json.load(f)
    except (OSError, ValueError):
        return
    evs = payload.get("events") or []
    stats = payload.get("stats") or {}
    print()
    print("wide events in bundle: %d (emitted %s, dropped %s)"
          % (len(evs), stats.get("emitted", "?"),
             stats.get("dropped", "?")))
    counts = {}
    for ev in evs:
        key = (str(ev.get("kind")), str(ev.get("outcome")))
        counts[key] = counts.get(key, 0) + 1
    for (kind, outcome), n in sorted(counts.items()):
        print("  %-20s %-10s %d" % (kind, outcome, n))
    bad = [e for e in evs if e.get("outcome") != "ok"]
    for ev in bad[-5:]:
        print("  last %s: span %s %s" % (
            ev.get("outcome"), ev.get("span_id"),
            " ".join("%s=%s" % (k, ev[k])
                     for k in ("stage", "reason", "error_kind")
                     if ev.get(k) is not None)))


def _stitch_fleet(spool, out):
    """--fleet: stitch a spool dir's per-rank traces into one pod
    timeline via the fleet collector (stdlib-only load through
    fleetz.load_fleet); returns (payload, spool problems)."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from fleetz import load_fleet

    payload, problems = load_fleet().stitch_traces(spool)
    if out:
        with open(out, "w") as f:
            json.dump(payload, f)
        fl = payload.get("otherData", {}).get("fleet", {})
        print("wrote %s (%d rank(s) stitched, %s skipped)"
              % (out, len(fl.get("ranks", [])), fl.get("skipped", 0)))
    return payload, problems


def main(argv=None):
    p = argparse.ArgumentParser(
        description="Summarize/validate mxnet_tpu chrome-trace exports")
    p.add_argument("paths", nargs="+", metavar="path",
                   help="trace JSON file(s) or flight-recorder bundle "
                        "directory; with --fleet, one spool dir")
    p.add_argument("--top", type=int, default=20,
                   help="rows per section (default 20)")
    p.add_argument("--tree", action="store_true",
                   help="print the span hierarchy of the slowest roots")
    p.add_argument("--top-ops", type=int, default=0, metavar="N",
                   help="print the N most expensive timeline ops with "
                        "total time and est. HBM bytes")
    p.add_argument("--fleet", action="store_true",
                   help="treat the path as a fleet spool dir and stitch "
                        "the per-rank traces into one pod timeline")
    p.add_argument("--out", help="write the loaded (or stitched) trace "
                                 "payload to this JSON file")
    args = p.parse_args(argv)

    if args.fleet:
        if len(args.paths) != 1:
            p.error("--fleet takes exactly one spool dir")
        payload, spool_problems = _stitch_fleet(args.paths[0], args.out)
        for msg in spool_problems:
            print("trace_view: fleet: %s" % msg, file=sys.stderr)
        fl = (payload.get("otherData") or {}).get("fleet") or {}
        stitched = fl.get("ranks") or []
        stale = fl.get("stale") or []
        if not stitched:
            # nothing merged: diagnose instead of validating an empty
            # timeline as a success
            print("trace_view: fleet: no rank traces stitched from %s "
                  "— no durable snapshots (wrong spool dir, or no "
                  "publisher attached)?  %d torn snapshot(s)"
                  % (args.paths[0], fl.get("torn_snapshots", 0)),
                  file=sys.stderr)
            return 1
        if stale and len(stale) >= len(stitched) and \
                all(r in stale for r in stitched):
            print("trace_view: fleet: every stitched rank (%s) is "
                  "STALE — the job is dead or the staleness cut is "
                  "too tight; the timeline below is historical"
                  % ",".join(str(r) for r in stale), file=sys.stderr)
            summarize(payload, args.top)
            return 1
        problems = validate(payload)
        summarize(payload, args.top)
        if args.tree:
            print_tree(payload, args.top)
        if problems:
            print()
            for msg in problems:
                print("INVARIANT VIOLATION: %s" % msg, file=sys.stderr)
            return 1
        return 0

    datas = [load_trace(path) for path in args.paths]
    # cross-file parent resolution: one capture exported as several
    # files (bundle split, per-rank spool) must validate as a whole
    all_ids = set()
    for data in datas:
        all_ids |= span_ids(data)
    exit_code = 0
    for path, data in zip(args.paths, datas):
        if len(datas) > 1:
            print("== %s ==" % path)
        problems = validate(data, known_span_ids=all_ids)
        summarize(data, args.top)
        if os.path.isdir(path):
            print_bundle_events(os.path.join(path, "events.json"))
        if args.top_ops:
            print_top_ops(data, args.top_ops)
        if args.tree:
            print_tree(data, args.top)
        if problems:
            print()
            for msg in problems:
                print("INVARIANT VIOLATION: %s" % msg, file=sys.stderr)
            exit_code = 1
        if len(datas) > 1:
            print()
    if args.out and datas:
        with open(args.out, "w") as f:
            json.dump(datas[0] if len(datas) == 1 else
                      {"traceEvents": [ev for d in datas
                                       for ev in d["traceEvents"]],
                       "otherData": {"merged_from": list(args.paths)}},
                      f)
    return exit_code


if __name__ == "__main__":
    sys.exit(main())
