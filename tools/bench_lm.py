"""Benchmark: transformer-LM training throughput (tokens/sec + MFU).

The second hot-path profile next to bench.py's ResNet-50 (ROADMAP "New
workload"): a decoder-only LM (examples/transformer_lm.py) trained by
ShardedTrainer over a named dp x fsdp x tp mesh with a spec-rule layout
(docs/sharding.md).  Emits ONE ``BENCH {json}`` marker line on stdout
(a schema-versioned perf_ledger record, appended to the
MXNET_PERF_LEDGER run ledger when set) carrying
``tokens_per_sec``, ``mfu`` (model-FLOPs accounting over the PR 4 peak
gauge), and the ``mesh_shape``/``layout`` the number was measured under
— so the perf trajectory is attributable to topology.  Since ISSUE 10
the run measures BOTH dispatch modes — synchronous per-step and async
+ K-step fused loop — and reports ``tokens_per_sec_sync``/``_async``,
``async_speedup``, ``steps_per_call`` and the per-phase
``host_gap_seconds`` p50; ``--trace-out`` writes the unified chrome
trace that ``tools/autotune.py --lm`` folds into the fusion cost
table.

    # 8-virtual-device CPU harness, canonical LLM layout:
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/bench_lm.py --mesh dp=2,fsdp=2,tp=2 --layout fsdp_tp

    # real chip (defaults scale up on accelerator backends):
    python tools/bench_lm.py --mesh fsdp=4,tp=2

Progress goes to stderr; stdout is the marked record line only.
"""
import argparse
import os
import sys
import time

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
for p in (REPO, os.path.join(REPO, "examples")):
    if p not in sys.path:
        sys.path.insert(0, p)

_T0 = time.time()


def log(msg):
    print("[bench_lm %6.1fs] %s" % (time.time() - _T0, msg),
          file=sys.stderr, flush=True)


def ledger_records(result):
    """perf_ledger record(s) for one bench_lm run: classic fields stay
    top-level, topology/precision ALSO stamp provenance (the schema
    guard test calls this with a canned result)."""
    from mxnet_tpu import perf_ledger

    prov = {"mesh_shape": result.get("mesh_shape"),
            "layout": result.get("layout"),
            "dtype_policy": result.get("dtype_policy"),
            "steps_per_call": result.get("steps_per_call", 1)}
    fields = {k: v for k, v in result.items()
              if k not in ("metric", "value", "unit", "attribution")}
    return [perf_ledger.make_record(
        result["metric"], result["value"], result["unit"], prov=prov,
        attribution=result.get("attribution"), **fields)]


def build_lm_trainer(mesh=None, layout=None, vocab=None, d_model=None,
                     n_heads=None, n_layers=None, seq=None, batch=None,
                     optimizer="adam", dtype_policy=None):
    """The LM benchmark-of-record configuration, shared with the tier-1
    smoke test (tests/test_sharding_layouts.py) so the committed BENCH
    numbers describe the exact program the suite guards.

    Returns (trainer, tokens, labels, cfg_dict)."""
    import jax

    import mxnet_tpu as mx
    from mxnet_tpu import nd, parallel
    from transformer_lm import TransformerLM, lm_loss_fn

    on_tpu = any(d.platform != "cpu" for d in jax.devices())
    # accelerator defaults vs CPU smoke defaults (bench.py discipline:
    # the CPU harness proves the program, the chip proves the number)
    vocab = vocab or (32000 if on_tpu else 256)
    d_model = d_model or (512 if on_tpu else 64)
    n_heads = n_heads or (8 if on_tpu else 4)
    n_layers = n_layers or (8 if on_tpu else 2)
    seq = seq or (512 if on_tpu else 32)
    batch = batch or (32 if on_tpu else 8)

    # precision: explicit dtype_policy= wins; default is the mixed
    # recipe on the chip (supersedes the old blanket bf16 cast, which
    # also bf16-rounded the f32 token-id carriers) and f32 on CPU
    if dtype_policy is None:
        dtype_policy = os.environ.get("BENCH_DTYPE_POLICY") or             ("bf16_mixed" if on_tpu else None)
    lm = TransformerLM(vocab_size=vocab, d_model=d_model, n_heads=n_heads,
                       n_layers=n_layers, max_len=max(seq, 64))
    lm.initialize(mx.init.Xavier())
    trainer = parallel.ShardedTrainer(
        lm, lm_loss_fn(vocab), mesh=mesh, layout=layout,
        optimizer=optimizer, optimizer_params={"learning_rate": 1e-3},
        dtype_policy=dtype_policy)
    rng = np.random.RandomState(0)
    tokens = nd.array(rng.randint(0, vocab, (batch, seq))
                      .astype(np.float32))
    labels = nd.array(rng.randint(0, vocab, (batch, seq))
                      .astype(np.float32))
    cfg = dict(vocab=vocab, d_model=d_model, n_heads=n_heads,
               n_layers=n_layers, seq=seq, batch=batch, on_tpu=on_tpu,
               flops_per_token=lm.flops_per_token(seq_len=seq))
    return trainer, tokens, labels, cfg


def run(mesh=None, layout=None, steps=20, warmup=2, steps_per_call=None,
        trace_out=None, dtype_compare=False, **model_kw):
    import jax

    from mxnet_tpu import telemetry, tracing

    telemetry.enable()  # MFU gauge + collective/state-bytes accounting
    if trace_out:
        # unified chrome trace of the measured run: the attention/
        # matmul profile tools/autotune.py --lm folds into the fusion
        # cost table (same artifact as tracing.export_trace)
        tracing.enable()
        from mxnet_tpu import profiler

        profiler.set_config(aggregate_stats=True)
    trainer, tokens, labels, cfg = build_lm_trainer(
        mesh=mesh, layout=layout, **model_kw)
    k = int(steps_per_call) if steps_per_call else \
        (4 if cfg["on_tpu"] else 2)
    if not cfg["on_tpu"]:
        # the LM smoke model is ms-scale per step: 12 steps keep the
        # sync-vs-async A/B above the noise floor without moving the
        # suite budget (bench.py's ResNet stays at 4)
        steps = min(steps, 12)
        warmup = min(warmup, 1)
    log("devices=%d mesh=%s layout=%s model=%s"
        % (len(jax.devices()), trainer.mesh_shape, trainer.layout_name,
           {k_: cfg[k_] for k_ in ("vocab", "d_model", "n_heads",
                                   "n_layers", "seq", "batch")}))
    xs, ys = trainer.shard_batch(tokens, labels)

    warmup_step_secs = []
    for i in range(max(warmup, 1)):
        t_s = time.perf_counter()
        loss = trainer.step([xs], ys)
        jax.block_until_ready(loss)
        warmup_step_secs.append(round(time.perf_counter() - t_s, 3))
        log("warmup step %d done (loss=%.4f, %.1fs)"
            % (i, float(loss), warmup_step_secs[-1]))

    # phase 1 — synchronous per-step dispatch (historical semantics)
    telemetry.reset()
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = trainer.step([xs], ys)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0
    gap_sync = telemetry.HOST_GAP_SECONDS.quantile(0.5, loop="sharded")
    log("[sync] %d steps in %.3fs (loss=%.4f)" % (steps, dt, float(loss)))

    # phase 2 — async dispatch + K-step fused loop (ISSUE 10)
    trainer.configure_overlap(async_metrics=True, steps_per_call=k)
    fused = [([xs], ys)] * k
    losses = trainer.step_many(fused)
    jax.block_until_ready(losses)
    trainer.drain()
    telemetry.reset()
    calls = max(1, steps // k)
    t0 = time.perf_counter()
    for _ in range(calls):
        losses = trainer.step_many(fused)
    jax.block_until_ready(losses)
    trainer.drain()
    dt_async = time.perf_counter() - t0
    gap_async = telemetry.HOST_GAP_SECONDS.quantile(0.5, loop="sharded")
    # step-time attribution over the async (headline) phase — rides
    # the BENCH record so perf_gate can name the moving bucket
    breakdown = trainer.step_breakdown()
    if breakdown is not None:
        log("\n" + breakdown.describe())
    log("[async] %d steps (%d fused calls of %d) in %.3fs"
        % (calls * k, calls, k, dt_async))

    tokens_per_step = cfg["batch"] * cfg["seq"]
    tps_sync = tokens_per_step * steps / dt
    tps = tokens_per_step * calls * k / dt_async
    # MFU two ways: the XLA cost-analysis gauge (exact program FLOPs)
    # when a peak is known, else the 6N analytic accounting only
    peak = telemetry.peak_flops()
    step_secs = dt_async / (calls * k)
    model_flops = cfg["flops_per_token"] * tokens_per_step
    mfu = None
    # on the CPU harness the docs/mfu_probe.json peak describes the
    # chip, not this host — only report MFU when the peak matches the
    # backend (or the operator pinned one via MXNET_PEAK_TFLOPS)
    if peak and (cfg["on_tpu"] or os.environ.get("MXNET_PEAK_TFLOPS")):
        mfu = round(model_flops / step_secs / peak, 4)
    result = {
        "metric": "transformer_lm_train_tokens_per_sec",
        "value": round(tps, 2),
        "unit": "tokens/sec",
        "tokens_per_sec": round(tps, 2),
        "tokens_per_sec_sync": round(tps_sync, 2),
        "tokens_per_sec_async": round(tps, 2),
        "async_speedup": round(tps / tps_sync, 3) if tps_sync else None,
        "steps_per_call": k,
        "async_metrics": True,
        "host_gap_seconds": {
            "sync": round(gap_sync, 6) if gap_sync is not None else None,
            "async": round(gap_async, 6) if gap_async is not None
            else None},
        "mfu": mfu,
        "model_flops_per_step": model_flops,
        "mesh_shape": trainer.mesh_shape,
        "layout": trainer.layout_name,
        "batch": cfg["batch"],
        "seq_len": cfg["seq"],
        "warmup_step_seconds": warmup_step_secs,
        # precision attribution (docs/mixed_precision.md)
        "dtype_policy": trainer.dtype_policy_tag,
        "loss_scale": trainer.loss_scale(),
        "loss_scale_backoffs": trainer.skipped_steps
        if trainer.dtype_policy is not None
        and trainer.dtype_policy.loss_scaling else None,
    }
    if breakdown is not None:
        result["attribution"] = breakdown.as_dict()
    if dtype_compare:
        # one short synchronous phase per policy on a fresh trainer:
        # the f32-vs-bf16 A/B the on-chip payoff sweep flips on
        comp = {}
        mk = {k: v for k, v in model_kw.items() if k != "dtype_policy"}
        for pol in ("f32", "bf16_mixed"):
            t2, tok2, lab2, c2 = build_lm_trainer(
                mesh=mesh, layout=layout, dtype_policy=pol, **mk)
            x2, y2 = t2.shard_batch(tok2, lab2)
            loss2 = t2.step([x2], y2)
            jax.block_until_ready(loss2)
            t0 = time.perf_counter()
            for _ in range(steps):
                loss2 = t2.step([x2], y2)
            jax.block_until_ready(loss2)
            dt2 = time.perf_counter() - t0
            t2.drain()
            comp[t2.dtype_policy_tag] = {
                "tokens_per_sec": round(
                    c2["batch"] * c2["seq"] * steps / dt2, 2),
                "loss_scale": t2.loss_scale(),
            }
            log("[dtype %s] %d steps in %.3fs"
                % (t2.dtype_policy_tag, steps, dt2))
        result["dtype_compare"] = comp
    if trace_out:
        tracing.export_trace(trace_out)
        log("unified trace written to %s" % trace_out)
    return result


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--mesh", default=None,
                   help="mesh spec, e.g. dp=2,fsdp=2,tp=2 (default: "
                        "MXNET_MESH, else single device)")
    p.add_argument("--layout", default=None,
                   help="layout name (default: MXNET_LAYOUT, else the "
                        "canonical layout for the mesh axes)")
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--warmup", type=int, default=2)
    p.add_argument("--steps-per-call", type=int, default=None,
                   help="K for the fused-loop phase (default: 4 on "
                        "TPU, 2 on the CPU harness)")
    p.add_argument("--dtype-policy", default=None,
                   help="mixed-precision dtype policy for the measured "
                        "trainer (f32/bf16_mixed/bf16_pure; default: "
                        "BENCH_DTYPE_POLICY, else bf16_mixed on TPU)")
    p.add_argument("--dtype-compare", action="store_true",
                   help="also measure one short f32 AND bf16_mixed "
                        "phase (fresh trainers) and emit dtype_compare")
    p.add_argument("--trace-out", default=None,
                   help="write the measured run's unified chrome trace "
                        "here (tools/autotune.py --lm consumes it)")
    p.add_argument("--vocab", type=int, default=None)
    p.add_argument("--d-model", type=int, default=None)
    p.add_argument("--n-heads", type=int, default=None)
    p.add_argument("--n-layers", type=int, default=None)
    p.add_argument("--seq", type=int, default=None)
    p.add_argument("--batch", type=int, default=None)
    a = p.parse_args(argv)
    result = run(mesh=a.mesh, layout=a.layout, steps=a.steps,
                 warmup=a.warmup, steps_per_call=a.steps_per_call,
                 trace_out=a.trace_out, dtype_compare=a.dtype_compare,
                 vocab=a.vocab, d_model=a.d_model,
                 n_heads=a.n_heads, n_layers=a.n_layers, seq=a.seq,
                 batch=a.batch, dtype_policy=a.dtype_policy)
    from mxnet_tpu import perf_ledger

    for rec in ledger_records(result):
        perf_ledger.emit(rec)
    return 0


if __name__ == "__main__":
    sys.exit(main())
