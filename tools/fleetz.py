#!/usr/bin/env python3
"""Fleet observatory CLI: merge a spool dir's per-rank snapshots and
name the straggler.

Usage:
    python tools/fleetz.py SPOOL                 # human-readable table
    python tools/fleetz.py SPOOL --json          # the /fleetz payload
    python tools/fleetz.py SPOOL --stale-after 5 # custom staleness cut
    python tools/fleetz.py SPOOL --top 3         # top-N merged counters

Stdlib-only (acceptance criterion): ``mxnet_tpu/fleet.py`` is loaded
by file path without importing the ``mxnet_tpu`` package (whose
``__init__`` pulls jax) — the same trick ``perf_report.py`` uses for
the perf ledger.  Other tools (``trace_view.py --fleet``,
``telemetry_dump.py --merge``) import :func:`load_fleet` from here so
there is exactly one loader.
"""
from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
_FLEET_PY = os.path.join(_HERE, os.pardir, "mxnet_tpu", "fleet.py")


def load_fleet():
    """The fleet module, without importing the mxnet_tpu package: the
    already-imported module when running inside the package (so state
    like the active spool is shared), else a bare file-path load."""
    mod = sys.modules.get("mxnet_tpu.fleet")
    if mod is not None:
        return mod
    spec = importlib.util.spec_from_file_location(
        "mxnet_tpu.fleet", os.path.abspath(_FLEET_PY))
    mod = importlib.util.module_from_spec(spec)
    sys.modules["mxnet_tpu.fleet"] = mod
    try:
        spec.loader.exec_module(mod)
    except BaseException:
        sys.modules.pop("mxnet_tpu.fleet", None)
        raise
    return mod


def _fmt(v, fmt="%.2f"):
    return fmt % v if isinstance(v, (int, float)) else "-"


def render(view, top=0):
    """Human-readable fleetz report."""
    lines = []
    if not view.get("active"):
        lines.append("fleet: inactive (%s)" % view.get("error", "?"))
        return "\n".join(lines)
    lines.append("fleet spool: %s" % view["spool"])
    header = "%-5s %-8s %-6s %-8s %-7s %-14s %-10s %s" % (
        "rank", "pid", "seq", "age_s", "stale", "wall_ms/step",
        "offset_s", "buckets_ms/step")
    lines.append(header)
    for rank, row in sorted(view["ranks"].items(), key=lambda kv: int(kv[0])):
        buckets = row.get("buckets_ms_per_step") or {}
        btxt = " ".join("%s=%.2f" % (k, v) for k, v in sorted(
            buckets.items()) if isinstance(v, (int, float)))
        lines.append("%-5s %-8s %-6s %-8s %-7s %-14s %-10s %s" % (
            rank, row.get("pid", "-"), row.get("seq", "-"),
            _fmt(row.get("age_s")), "STALE" if row.get("stale") else "ok",
            _fmt(row.get("wall_ms_per_step")),
            _fmt(row.get("clock_offset_s"), "%+.3f"), btxt))
    rep = view.get("straggler") or {}
    if rep.get("straggler") is not None:
        lines.append("straggler: rank %s (skew %.2fx, bucket %s %+.2f "
                     "ms/step vs fleet median)" % (
                         rep["straggler"], rep["skew"], rep["bucket"],
                         rep.get("bucket_delta_ms_per_step") or 0.0))
    else:
        lines.append("straggler: none (%s)" % rep.get("reason", "?"))
    if view.get("torn_snapshots"):
        lines.append("warning: %d torn snapshot(s) skipped"
                     % view["torn_snapshots"])
    for prob in view.get("problems", []):
        lines.append("warning: %s" % prob)
    if top:
        merged = view.get("merged_metrics") or {}
        counters = []
        for name, fam in merged.items():
            if fam.get("type") != "counter":
                continue
            for s in fam.get("series", []):
                v = s.get("value", 0)
                if isinstance(v, (int, float)) and v:
                    counters.append((v, name, s.get("labels") or {}))
        counters.sort(key=lambda t: (-t[0], t[1]))
        if counters:
            lines.append("top merged counters:")
            for v, name, labels in counters[:top]:
                ltxt = ",".join("%s=%s" % kv for kv in sorted(
                    labels.items()))
                lines.append("  %-52s %s" % (
                    name + ("{%s}" % ltxt if ltxt else ""), v))
    return "\n".join(lines)


def main(argv=None):
    p = argparse.ArgumentParser(description="merge a fleet spool and "
                                            "report the straggler")
    p.add_argument("spool", help="fleet spool directory")
    p.add_argument("--stale-after", type=float, default=None,
                   help="staleness cut in seconds (MXNET_FLEET_STALE)")
    p.add_argument("--json", action="store_true",
                   help="emit the raw /fleetz payload")
    p.add_argument("--top", type=int, default=5,
                   help="show the top-N merged counters (0 = none)")
    args = p.parse_args(argv)
    fleet = load_fleet()
    view = fleet.fleetz(spool=args.spool, stale_after=args.stale_after)
    if args.json:
        json.dump(view, sys.stdout, indent=2, sort_keys=True)
        sys.stdout.write("\n")
    else:
        print(render(view, top=args.top))
    if not view.get("active"):
        return 1
    # an empty merge is a diagnosis, not a report: a spool with no
    # durable snapshots (wrong dir? publishers never attached?) or one
    # where every rank went stale (job dead? staleness cut too tight?)
    # must say so and fail, never exit 0 with an empty table
    ranks = view.get("ranks") or {}
    if not ranks:
        print("fleetz: no durable rank snapshots in %s — is this the "
              "right spool dir, and did any FleetPublisher attach? "
              "(%d torn snapshot(s))"
              % (view["spool"], view.get("torn_snapshots", 0)),
              file=sys.stderr)
        return 1
    if all(row.get("stale") for row in ranks.values()):
        print("fleetz: all %d rank snapshot(s) in %s are stale "
              "(older than %.1fs) — the job is dead or the "
              "--stale-after cut is too tight"
              % (len(ranks), view["spool"],
                 view.get("stale_after_s", 0.0)),
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
