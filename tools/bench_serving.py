"""Small-batch serving throughput (VERDICT r3 weak #1 / next-round #1).

Measures bs32 ResNet-50 inference through mxnet_tpu.serving.Predictor in
the modes that matter:

- ``host-uint8``: raw uint8 NCHW batches fed from the host, normalized
  on device (the fixed serving path — minimum possible bytes/image over
  the host->device link, uploads overlapped with compute).
- ``device``: input already device-resident (a cache-serving scenario) —
  isolates the compiled chain program's own throughput.
- ``link``: measured upload bandwidth for exactly one batch's bytes,
  giving the physics ceiling  bw / bytes_per_image  that ``host-uint8``
  should saturate.  On this dev environment the chip sits behind a
  network tunnel (~5-30 MB/s, ~100 ms RTT — docs/perf_notes.md upload
  table); on a real TPU host the same pipeline rides PCIe (>10 GB/s)
  and becomes compute-bound at the ``device`` number.

Timing follows docs/perf_notes.md methodology: the clock stops only
after every output batch has been fetched to the host, which cannot
complete before the device work has.

A second mode, ``--load``, is the sustained open-loop harness for the
async tier (docs/serving.md): Poisson arrivals at a swept target QPS
against an AsyncPredictor, one BENCH-comparable JSON line per rate
with p50/p99/p999 latency, shed rate, timeout rate, and goodput.
Open-loop matters: a closed loop self-throttles when the server slows
and hides exactly the overload regime the admission control exists
for.

Usage: python tools/bench_serving.py [--json docs/serving_bench.json]
       python tools/bench_serving.py --load --qps 20,50,100 \
           [--duration 5] [--deadline-ms 200] [--replicas 1] \
           [--gateway] [--json docs/serving_load.json]
"""
import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu.gluon.model_zoo import vision  # noqa: E402
from mxnet_tpu.serving import Predictor, uint8_normalizer  # noqa: E402
from mxnet_tpu.serving_async import (AsyncPredictor,  # noqa: E402
                                     DeadlineExceeded, Overloaded,
                                     ServingError)


def ledger_records(results):
    """perf_ledger record(s) for one bench_serving run — the three
    throughput modes of the default run, or one goodput record per
    swept rate for ``--load`` results (detected by the ``sweep`` key).
    The tier-1 schema guard calls this with canned results."""
    from mxnet_tpu import perf_ledger

    recs = []
    if "sweep" in results:
        meta = {k: v for k, v in results.items() if k != "sweep"}
        for row in results["sweep"]:
            fields = dict(meta)
            fields.update(row)
            recs.append(perf_ledger.make_record(
                "serving_load_goodput_qps@%g" % row["target_qps"],
                row["goodput_qps"], "qps", **fields))
        return recs
    for metric, key in (
            ("resnet50_serving_host_uint8_img_s", "host_uint8_img_s"),
            ("resnet50_serving_device_img_s", "device_resident_img_s"),
            ("resnet50_serving_device_top5_img_s", "device_top5_img_s")):
        if results.get(key) is not None:
            recs.append(perf_ledger.make_record(
                metric, results[key], "images/sec", **results))
    return recs


def measure_link_bw(shape, chain=8, reps=2):
    """Upload bandwidth in serving's own regime: a stream of ``chain``
    per-batch async device_puts, forced together by one host fetch."""
    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    force = jax.jit(
        lambda *a: sum(jnp.reshape(t, (-1,))[0].astype(jnp.float32)
                       for t in a))
    xs = [np.random.randint(0, 255, shape, np.uint8)
          for _ in range(chain)]
    ys = [jax.device_put(x, dev) for x in xs]
    float(force(*ys))
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        ys = [jax.device_put(x, dev) for x in xs]
        float(force(*ys))
        best = min(best, time.perf_counter() - t0)
    return sum(x.nbytes for x in xs) / best


def run(batch=32, n_batches=32, chain=8, dtype="bfloat16", json_path=None):
    import jax

    net = vision.resnet50_v1(classes=1000)
    net.initialize(mx.init.Xavier())
    if dtype == "bfloat16":
        net.cast("bfloat16")
    prep = uint8_normalizer(dtype=dtype)
    raw = np.random.randint(0, 255, (batch, 3, 224, 224), np.uint8)
    pred, _ = Predictor.from_block(net, raw, chain=chain, preprocess=prep)

    results = {"batch": batch, "n_batches": n_batches, "chain": chain,
               "dtype": dtype}

    bw = measure_link_bw(raw.shape, chain=chain)
    ceiling = bw / (raw.nbytes / batch)
    results["link_MBps"] = round(bw / 1e6, 2)
    results["link_ceiling_img_s"] = round(ceiling, 1)
    print("host->device link: %.1f MB/s -> physics ceiling %.0f img/s "
          "at %.3f MB/img uint8"
          % (bw / 1e6, ceiling, raw.nbytes / batch / 1e6), flush=True)

    # --- host-uint8 streaming (the real serving path) ---
    batches = [np.random.randint(0, 255, raw.shape, np.uint8)
               for _ in range(n_batches)]
    list(pred.predict(batches[:chain]))          # warm/compile
    t0 = time.time()
    outs = list(pred.predict(batches))
    dt = time.time() - t0
    assert len(outs) == n_batches and outs[0].shape[0] == batch
    ips = batch * n_batches / dt
    results["host_uint8_img_s"] = round(ips, 1)
    results["link_efficiency"] = round(ips / ceiling, 3) if ceiling else None
    print("host-uint8 : %8.1f img/s  (%.2fs, %d x bs%d)  = %.0f%% of link "
          "ceiling" % (ips, dt, n_batches, batch, 100 * ips / ceiling),
          flush=True)

    # --- device-resident (compiled program throughput) ---
    dev = jax.devices()[0]
    dev_batches = [jax.device_put(b, dev) for b in batches]
    jax.block_until_ready(dev_batches)
    list(pred.predict(dev_batches[:chain]))
    t0 = time.time()
    outs = list(pred.predict(dev_batches))
    dt = time.time() - t0
    ips_dev = batch * n_batches / dt
    results["device_resident_img_s"] = round(ips_dev, 1)
    print("device     : %8.1f img/s  (%.2fs)" % (ips_dev, dt), flush=True)

    # --- device-resident + device-side top-5 (classify-API shape:
    # fetch 5 int32/row instead of 1000 logits — the realistic serving
    # response, and it keeps the tunnel out of the output path too) ---
    import jax.numpy as jnp

    top5 = Predictor.from_block(
        net, raw, chain=chain, preprocess=prep,
        postprocess=lambda o: jax.lax.top_k(o.astype(jnp.float32), 5)[1])[0]
    list(top5.predict(dev_batches[:chain]))
    t0 = time.time()
    outs5 = list(top5.predict(dev_batches))
    dt = time.time() - t0
    assert outs5[0].shape == (batch, 5)
    ips5 = batch * n_batches / dt
    results["device_top5_img_s"] = round(ips5, 1)
    print("device+top5: %8.1f img/s  (%.2fs)" % (ips5, dt), flush=True)

    anchor = 2086.0  # V100 fp16 bs32, reference docs/faq/perf.md:181-199
    results["anchor_v100_img_s"] = anchor
    results["device_vs_anchor"] = round(ips_dev / anchor, 3)
    print("vs V100 fp16 anchor (%.0f): device %.2fx, host-fed %.2fx "
          "(tunnel-capped)" % (anchor, ips_dev / anchor, ips / anchor),
          flush=True)

    from mxnet_tpu import perf_ledger

    for rec in ledger_records(results):
        perf_ledger.emit(rec)

    if json_path:
        with open(json_path, "w") as f:
            json.dump(results, f, indent=1)
        print("wrote", json_path)
    return results


def _pctl(sorted_vals, q):
    if not sorted_vals:
        return None
    i = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[i]


def _load_predictor(batch_rows, feat, replicas, chain):
    """Small-MLP AsyncPredictor: the load harness measures queueing
    dynamics (admission, deadlines, shed), not model FLOPs — a big model
    would just move every sweep point into the same saturated regime."""
    import jax

    from mxnet_tpu.gluon import nn

    net = nn.HybridSequential()
    net.add(nn.Dense(64, activation="relu"), nn.Dense(8))
    net.initialize()
    example = np.random.rand(batch_rows, feat).astype(np.float32)
    return AsyncPredictor.from_block(
        net, example, replicas=replicas, chain=chain,
        batch_window_ms=1.0), len(jax.devices())


class _HttpFuture:
    """stdlib-HTTP stand-in for a ServingFuture: one daemon thread per
    request (open loop — submit never blocks on the server), resolving
    to the parsed body or the wire code mapped back onto the typed
    taxonomy (429/503 -> Overloaded, 504/408 -> DeadlineExceeded), so
    the sweep's accounting is transport-agnostic."""

    def __init__(self, host, port, model, payload, deadline_ms):
        import threading

        self.resolved_at = None
        self._out = None
        self._exc = None
        self._done = threading.Event()
        t = threading.Thread(
            target=self._run,
            args=(host, port, model, payload, deadline_ms), daemon=True)
        t.start()

    def _run(self, host, port, model, payload, deadline_ms):
        import http.client

        try:
            conn = http.client.HTTPConnection(host, port, timeout=30)
            headers = {"Content-Type": "application/json",
                       "Content-Length": str(len(payload))}
            if deadline_ms:
                headers["X-Deadline-Ms"] = str(deadline_ms)
            conn.request("POST", "/v1/predict/%s" % model, body=payload,
                         headers=headers)
            resp = conn.getresponse()
            body = resp.read()
            self.resolved_at = time.monotonic()
            if resp.status == 200:
                self._out = json.loads(body)["outputs"]
            elif resp.status == 429:
                self._exc = Overloaded("queue", "HTTP 429")
            elif resp.status == 503:
                self._exc = Overloaded("shutdown", "HTTP 503")
            elif resp.status in (504, 408):
                self._exc = DeadlineExceeded("dispatch",
                                             "HTTP %d" % resp.status)
            else:
                self._exc = ServingError("HTTP %d: %s"
                                         % (resp.status, body[:200]))
            conn.close()
        except Exception as e:
            self.resolved_at = time.monotonic()
            self._exc = ServingError("transport: %s" % e)
        finally:
            self._done.set()

    def result(self, timeout=None):
        if not self._done.wait(timeout):
            raise TimeoutError("HTTP request unresolved")
        if self._exc is not None:
            raise self._exc
        return self._out

    def cancel(self):
        return False


def run_load(qps_list, duration=5.0, batch_rows=8, feat=16, rows=1,
             chain=8, replicas=1, deadline_ms=200.0, seed=0,
             gateway=False, json_path=None):
    """Open-loop Poisson load sweep against the async tier.

    Per target QPS: submit ``rows``-row requests at exponential
    inter-arrival times for ``duration`` seconds (never waiting on the
    server — open loop), then join every future and report latency
    percentiles over completions plus shed/timeout/error rates over
    offered load.  One BENCH JSON line per rate.

    ``gateway=True`` drives the same sweep over real HTTP: an
    in-process :class:`mxnet_tpu.gateway.Gateway` routes ``load`` to
    the AsyncPredictor and every request rides a stdlib HTTP client
    (shed/timeout/p99 measured at the wire, same perf_ledger records —
    ``transport: "http"`` marks the rows).
    """
    from mxnet_tpu import telemetry as tel

    tel.enable()
    ap, n_devs = _load_predictor(batch_rows, feat, replicas, chain)
    req = np.random.RandomState(seed).rand(rows, feat).astype(np.float32)
    ap.predict(req, timeout=30)            # warm/compile off the clock
    out = {"mode": "open-loop-poisson", "duration_s": duration,
           "rows_per_request": rows, "batch_rows": batch_rows,
           "chain": chain, "replicas": replicas, "devices": n_devs,
           "deadline_ms": deadline_ms, "sweep": []}
    gw = None
    if gateway:
        from mxnet_tpu.gateway import Gateway

        # WFQ sized to the predictor's own pipeline capacity so the
        # gateway measures the backend's admission, not its own
        gw = Gateway(port=0, concurrency=max(16, 2 * chain),
                     queue_depth=256)
        gw.add_route("load", ap, kind="predict")
        payload = json.dumps({"rows": req.tolist()})
        out["transport"] = "http"

        def _submit(batch, deadline_ms=None):
            return _HttpFuture(gw.host, gw.port, "load", payload,
                               deadline_ms)
    else:
        _submit = ap.submit
    try:
        for qps in qps_list:
            rng = np.random.RandomState(seed)
            offered = shed = 0
            inflight = []
            start = time.monotonic()
            next_t = start
            end = start + duration
            while next_t < end:
                now = time.monotonic()
                if now < next_t:
                    time.sleep(next_t - now)
                offered += 1
                t0 = time.monotonic()
                try:
                    inflight.append(
                        (_submit(req, deadline_ms=deadline_ms), t0))
                except ServingError:
                    shed += 1
                next_t += rng.exponential(1.0 / qps)
            lats, timeouts, errors = [], 0, 0
            for fut, t0 in inflight:
                try:
                    fut.result(timeout=30)
                    lats.append(fut.resolved_at - t0)
                except Overloaded:
                    # HTTP transport learns a shed at response time
                    # (429/503), not at submit like in-process
                    shed += 1
                except DeadlineExceeded:
                    timeouts += 1
                except TimeoutError:
                    # future unresolved after 30 s (e.g. --deadline-ms 0
                    # past saturation): count it, keep the sweep's data
                    timeouts += 1
                    fut.cancel()
                except ServingError:
                    errors += 1
            # settle before the next rate: leftover queued/claimed work
            # from this rate must not contaminate the next measurement
            settle_end = time.monotonic() + 10.0
            while ap.stats()["inflight"] > 0 and \
                    time.monotonic() < settle_end:
                time.sleep(0.05)
            lats.sort()
            row = {
                "target_qps": qps,
                "offered": offered,
                "offered_qps": round(offered / duration, 1),
                "completed": len(lats),
                "goodput_qps": round(len(lats) / duration, 1),
                "shed": shed,
                "shed_rate": round(shed / offered, 4),
                "timeouts": timeouts,
                "timeout_rate": round(timeouts / offered, 4),
                "errors": errors,
                "p50_ms": round(1e3 * _pctl(lats, 0.50), 2) if lats
                else None,
                "p99_ms": round(1e3 * _pctl(lats, 0.99), 2) if lats
                else None,
                "p999_ms": round(1e3 * _pctl(lats, 0.999), 2) if lats
                else None,
            }
            out["sweep"].append(row)
            from mxnet_tpu import perf_ledger

            perf_ledger.emit(ledger_records(
                {**{k: v for k, v in out.items() if k != "sweep"},
                 "sweep": [row]})[0])
    finally:
        if gw is not None:
            gw.close(timeout=5)
        ap.close(timeout=30)
    if json_path:
        with open(json_path, "w") as f:
            json.dump(out, f, indent=1)
        print("wrote", json_path)
    return out


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--n-batches", type=int, default=32)
    p.add_argument("--chain", type=int, default=8)
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--json", default=None)
    p.add_argument("--load", action="store_true",
                   help="open-loop Poisson QPS sweep vs AsyncPredictor")
    p.add_argument("--qps", default="20,50,100",
                   help="comma-separated target QPS sweep (--load)")
    p.add_argument("--duration", type=float, default=5.0)
    p.add_argument("--deadline-ms", type=float, default=200.0)
    p.add_argument("--replicas", type=int, default=1)
    p.add_argument("--rows", type=int, default=1,
                   help="rows per request (--load)")
    p.add_argument("--gateway", action="store_true",
                   help="drive the --load sweep over real HTTP "
                   "through an in-process serving gateway")
    a = p.parse_args()
    if a.load:
        run_load([float(q) for q in a.qps.split(",")],
                 duration=a.duration, chain=a.chain,
                 replicas=a.replicas, deadline_ms=a.deadline_ms,
                 rows=a.rows, gateway=a.gateway, json_path=a.json)
    else:
        run(a.batch, a.n_batches, chain=a.chain, dtype=a.dtype,
            json_path=a.json)
