"""Small-batch serving throughput (VERDICT r3 weak #1 / next-round #1).

Measures bs32 ResNet-50 inference through mxnet_tpu.serving.Predictor in
the modes that matter:

- ``host-uint8``: raw uint8 NCHW batches fed from the host, normalized
  on device (the fixed serving path — minimum possible bytes/image over
  the host->device link, uploads overlapped with compute).
- ``device``: input already device-resident (a cache-serving scenario) —
  isolates the compiled chain program's own throughput.
- ``link``: measured upload bandwidth for exactly one batch's bytes,
  giving the physics ceiling  bw / bytes_per_image  that ``host-uint8``
  should saturate.  On this dev environment the chip sits behind a
  network tunnel (~5-30 MB/s, ~100 ms RTT — docs/perf_notes.md upload
  table); on a real TPU host the same pipeline rides PCIe (>10 GB/s)
  and becomes compute-bound at the ``device`` number.

Timing follows docs/perf_notes.md methodology: the clock stops only
after every output batch has been fetched to the host, which cannot
complete before the device work has.

Usage: python tools/bench_serving.py [--json docs/serving_bench.json]
"""
import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu.gluon.model_zoo import vision  # noqa: E402
from mxnet_tpu.serving import Predictor, uint8_normalizer  # noqa: E402


def measure_link_bw(shape, chain=8, reps=2):
    """Upload bandwidth in serving's own regime: a stream of ``chain``
    per-batch async device_puts, forced together by one host fetch."""
    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    force = jax.jit(
        lambda *a: sum(jnp.reshape(t, (-1,))[0].astype(jnp.float32)
                       for t in a))
    xs = [np.random.randint(0, 255, shape, np.uint8)
          for _ in range(chain)]
    ys = [jax.device_put(x, dev) for x in xs]
    float(force(*ys))
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        ys = [jax.device_put(x, dev) for x in xs]
        float(force(*ys))
        best = min(best, time.perf_counter() - t0)
    return sum(x.nbytes for x in xs) / best


def run(batch=32, n_batches=32, chain=8, dtype="bfloat16", json_path=None):
    import jax

    net = vision.resnet50_v1(classes=1000)
    net.initialize(mx.init.Xavier())
    if dtype == "bfloat16":
        net.cast("bfloat16")
    prep = uint8_normalizer(dtype=dtype)
    raw = np.random.randint(0, 255, (batch, 3, 224, 224), np.uint8)
    pred, _ = Predictor.from_block(net, raw, chain=chain, preprocess=prep)

    results = {"batch": batch, "n_batches": n_batches, "chain": chain,
               "dtype": dtype}

    bw = measure_link_bw(raw.shape, chain=chain)
    ceiling = bw / (raw.nbytes / batch)
    results["link_MBps"] = round(bw / 1e6, 2)
    results["link_ceiling_img_s"] = round(ceiling, 1)
    print("host->device link: %.1f MB/s -> physics ceiling %.0f img/s "
          "at %.3f MB/img uint8"
          % (bw / 1e6, ceiling, raw.nbytes / batch / 1e6), flush=True)

    # --- host-uint8 streaming (the real serving path) ---
    batches = [np.random.randint(0, 255, raw.shape, np.uint8)
               for _ in range(n_batches)]
    list(pred.predict(batches[:chain]))          # warm/compile
    t0 = time.time()
    outs = list(pred.predict(batches))
    dt = time.time() - t0
    assert len(outs) == n_batches and outs[0].shape[0] == batch
    ips = batch * n_batches / dt
    results["host_uint8_img_s"] = round(ips, 1)
    results["link_efficiency"] = round(ips / ceiling, 3) if ceiling else None
    print("host-uint8 : %8.1f img/s  (%.2fs, %d x bs%d)  = %.0f%% of link "
          "ceiling" % (ips, dt, n_batches, batch, 100 * ips / ceiling),
          flush=True)

    # --- device-resident (compiled program throughput) ---
    dev = jax.devices()[0]
    dev_batches = [jax.device_put(b, dev) for b in batches]
    jax.block_until_ready(dev_batches)
    list(pred.predict(dev_batches[:chain]))
    t0 = time.time()
    outs = list(pred.predict(dev_batches))
    dt = time.time() - t0
    ips_dev = batch * n_batches / dt
    results["device_resident_img_s"] = round(ips_dev, 1)
    print("device     : %8.1f img/s  (%.2fs)" % (ips_dev, dt), flush=True)

    # --- device-resident + device-side top-5 (classify-API shape:
    # fetch 5 int32/row instead of 1000 logits — the realistic serving
    # response, and it keeps the tunnel out of the output path too) ---
    import jax.numpy as jnp

    top5 = Predictor.from_block(
        net, raw, chain=chain, preprocess=prep,
        postprocess=lambda o: jax.lax.top_k(o.astype(jnp.float32), 5)[1])[0]
    list(top5.predict(dev_batches[:chain]))
    t0 = time.time()
    outs5 = list(top5.predict(dev_batches))
    dt = time.time() - t0
    assert outs5[0].shape == (batch, 5)
    ips5 = batch * n_batches / dt
    results["device_top5_img_s"] = round(ips5, 1)
    print("device+top5: %8.1f img/s  (%.2fs)" % (ips5, dt), flush=True)

    anchor = 2086.0  # V100 fp16 bs32, reference docs/faq/perf.md:181-199
    results["anchor_v100_img_s"] = anchor
    results["device_vs_anchor"] = round(ips_dev / anchor, 3)
    print("vs V100 fp16 anchor (%.0f): device %.2fx, host-fed %.2fx "
          "(tunnel-capped)" % (anchor, ips_dev / anchor, ips / anchor),
          flush=True)

    if json_path:
        with open(json_path, "w") as f:
            json.dump(results, f, indent=1)
        print("wrote", json_path)
    return results


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--n-batches", type=int, default=32)
    p.add_argument("--chain", type=int, default=8)
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--json", default=None)
    a = p.parse_args()
    run(a.batch, a.n_batches, chain=a.chain, dtype=a.dtype,
        json_path=a.json)
