"""Small-batch serving throughput (VERDICT round-2 weak #3 / task #8).

Measures bs32 ResNet-50 inference through mxnet_tpu.serving.Predictor at
several chain depths.  Timing follows docs/perf_notes.md methodology:
the clock stops only after every output batch has been fetched to the
host, which cannot complete before the device work has."""
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu.gluon.model_zoo import vision  # noqa: E402
from mxnet_tpu.serving import Predictor  # noqa: E402


def run(batch=32, n_batches=64, chains=(1, 4, 8, 16), dtype="bfloat16"):
    net = vision.resnet50_v1(classes=1000)
    net.initialize(mx.init.Xavier())
    if dtype == "bfloat16":
        net.cast("bfloat16")
    x = np.random.rand(batch, 3, 224, 224).astype(np.float32)
    if dtype == "bfloat16":
        import jax.numpy as jnp

        x = x.astype(jnp.bfloat16)
    results = {}
    for chain in chains:
        pred, ex = Predictor.from_block(net, mx.nd.array(
            np.asarray(x, np.float32)).astype(dtype) if dtype == "bfloat16"
            else mx.nd.array(x), chain=chain)
        batches = [np.asarray(ex)] * n_batches
        # warm (compile)
        list(pred.predict(batches[:chain]))
        t0 = time.time()
        outs = list(pred.predict(batches))
        dt = time.time() - t0
        assert len(outs) == n_batches and outs[0].shape[0] == batch
        ips = batch * n_batches / dt
        results[chain] = ips
        print("chain=%-3d  %8.1f img/s  (%.3fs for %d batches of %d)"
              % (chain, ips, dt, n_batches, batch))
    return results


if __name__ == "__main__":
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--n-batches", type=int, default=64)
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--chains", default="1,4,8,16",
                   help="comma-separated chain depths")
    a = p.parse_args()
    run(a.batch, a.n_batches,
        chains=tuple(int(c) for c in a.chains.split(",")),
        dtype=a.dtype)
