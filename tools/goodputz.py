#!/usr/bin/env python3
"""Goodput ledger CLI: merge a job dir's incarnation ledgers into one
job-lifetime goodput/badput report.

Usage:
    python tools/goodputz.py JOB_DIR           # human-readable report
    python tools/goodputz.py JOB_DIR --json    # the /goodputz payload

The report decomposes job wall-clock into goodput (productive steps
minus preemption lost work) and the badput buckets — lost_work,
compile, ckpt_save, ckpt_restore, data_wait, startup, drain, other —
with a per-incarnation table and MTTR between each kill and the first
productive step of the successor incarnation.  Torn or partial ledger
lines are skipped with a counted warning, never a crash.

Stdlib-only (acceptance criterion): ``mxnet_tpu/goodput.py`` is loaded
by file path without importing the ``mxnet_tpu`` package (whose
``__init__`` pulls jax) — the same trick ``fleetz.py`` uses for the
fleet collector.  ``perf_report.py --goodput`` imports
:func:`load_goodput` from here so there is exactly one loader.

Exit 0 on a rendered report, 1 when the job dir is missing/empty.
"""
from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
_GOODPUT_PY = os.path.join(_HERE, os.pardir, "mxnet_tpu", "goodput.py")


def load_goodput():
    """The goodput module, without importing the mxnet_tpu package:
    the already-imported module when running inside the package (so
    the active job dir is shared), else a bare file-path load."""
    mod = sys.modules.get("mxnet_tpu.goodput")
    if mod is not None:
        return mod
    spec = importlib.util.spec_from_file_location(
        "mxnet_tpu.goodput", os.path.abspath(_GOODPUT_PY))
    mod = importlib.util.module_from_spec(spec)
    sys.modules["mxnet_tpu.goodput"] = mod
    try:
        spec.loader.exec_module(mod)
    except BaseException:
        sys.modules.pop("mxnet_tpu.goodput", None)
        raise
    return mod


def main(argv=None):
    p = argparse.ArgumentParser(description="merge a goodput job dir "
                                            "and report goodput/badput")
    p.add_argument("dir", help="goodput job directory "
                               "(MXNET_GOODPUT_DIR)")
    p.add_argument("--json", action="store_true",
                   help="emit the raw /goodputz payload")
    args = p.parse_args(argv)
    goodput = load_goodput()
    payload = goodput.goodputz(dir=args.dir)
    if args.json:
        json.dump(payload, sys.stdout, indent=2, sort_keys=True)
        sys.stdout.write("\n")
    else:
        print(goodput.render_report(payload))
    if not payload.get("active"):
        print("goodputz: %s" % payload.get("error", "inactive"),
              file=sys.stderr)
        return 1
    if not payload.get("n_incarnations"):
        print("goodputz: no incarnation ledgers in %s — is this the "
              "right job dir, and did any GoodputRecorder begin?"
              % args.dir, file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
