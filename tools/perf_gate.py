"""Noise-aware perf regression gate over the BENCH run ledger.

Four rounds of headline benches (r02-r05) spread ~0.5% around 2183
img/s while real regressions hide below log tails — this gate makes
"did this PR regress a metric" a nonzero exit code instead of a
judgement call:

* **Bands are seeded from the baseline's own spread**: per metric,
  tolerance = max(--floor, --spread-factor x relative spread of the
  baseline samples).  A metric measured four times at +-0.5% gets a
  tight band; a CPU-noisy one earns a wide one.  ``--tolerance
  metric=0.08`` pins a metric explicitly.
* **Min-of-blocks aware**: multiple records of one metric within one
  run are repeated measurement blocks — each run reduces to its best
  block (max for throughput, min for latency) before comparison,
  mirroring the microbench methodology; the baseline reference is the
  median of per-run bests.
* **Direction comes from the unit** (images/sec, tokens/sec, qps, x
  = higher-better; seconds, ms = lower-better; unknown units fall
  back on the metric name, then higher-better).
* **Failures name the moving bucket**: when a metric regresses and
  both sides carry a step-time ``attribution``, the largest-moving
  bucket (device_compute / compile / aot_load / data_wait /
  host_other) is printed next to the metric — the gate says not just
  *that* the milliseconds went, but *where*.

Stdlib-only (perf_ledger loads standalone, no jax): the gate is a
seconds-level tier-1 smoke on CPU and a sub-second CI step anywhere.

    # candidate = newest run in the ledger, baseline = the rest:
    python tools/perf_gate.py --ledger perf_ledger.jsonl

    # explicit baseline files (legacy driver captures work too):
    python tools/perf_gate.py --baseline BENCH_r0*.json \
        --candidate perf_ledger.jsonl

Exit codes: 0 = within bands, 1 = regression (metric + bucket named),
2 = unusable input.
"""
import argparse
import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, HERE)

from perf_report import backfill_file, group_runs, pl  # noqa: E402

# metrics where a *drop* is the regression vs where a *rise* is.
# Latency units regress UPWARD: the decode tier's TTFT/per-token
# records (tools/bench_decode.py) are the first latency-bound headline
# metrics, and gating them higher-is-better would wave regressions
# through.  The paged-decode levers (ISSUE 16) gate by unit too:
# ``ratio`` (prefix hit rate) and ``tokens/step`` (accepted drafts per
# verify step) regress DOWNWARD, while the interference TTFT rides the
# existing ``ms`` rule.
_HIGHER_BETTER_UNITS = {"images/sec", "img/s", "tokens/sec", "qps", "x",
                        "bool", "flops", "gb/s", "tokens/sec/user",
                        "tokens/s/user", "ratio", "rate", "tokens/step"}
_LOWER_BETTER_UNITS = {"seconds", "s", "ms", "us", "bytes", "ms/token",
                       "ms/request"}


def higher_is_better(metric, unit):
    m = str(metric).lower()
    # goodput regresses DOWNWARD (a drop means more badput), and its
    # pct unit must never drift into a lower-better bucket: name-pin
    # the direction ahead of the unit tables so the intent survives
    # both a default flip and a future "pct" unit rule
    if m == "goodput_pct" or m.endswith("_goodput_pct"):
        return True
    u = str(unit).lower()
    if u in _HIGHER_BETTER_UNITS:
        return True
    if u in _LOWER_BETTER_UNITS:
        return False
    if m.endswith(("_seconds", "_ms", "_latency", "_overhead_ms_per_save",
                   "_bytes", "_ttft_p50", "_ttft_p99", "_interference_p99")):
        return False
    # name fallback for unitless paged-decode levers: hit rates and
    # accepted-drafts-per-step regress downward-is-bad (higher better),
    # which is also the default — listed here so the intent survives a
    # default flip
    return True


def load_records(paths):
    """Records from a mix of JSONL ledgers and legacy run files.  An
    unreadable/unparsable path is reported and skipped — when nothing
    loads the caller exits 2 (unusable input), never 1 (a crashed gate
    must not read as a perf regression in CI)."""
    records = []
    for path in paths:
        try:
            if path.endswith(".jsonl"):
                recs, problems = pl.read_ledger(path)
                for lineno, msg in problems:
                    print("perf_gate: %s:%d: %s" % (path, lineno, msg),
                          file=sys.stderr)
                records.extend(recs)
            else:
                records.extend(backfill_file(path))
        except (OSError, ValueError) as e:
            print("perf_gate: %s: unreadable (%s)" % (path, e),
                  file=sys.stderr)
    return records


def best_per_run(records, better_max):
    """{run_id: (best value, record that scored it)} — the
    min-of-blocks reduction (repeated records within a run are blocks)."""
    best = {}
    pick = max if better_max else min
    for r in records:
        v = r["value"]
        cur = best.get(r["run_id"])
        if cur is None or pick(v, cur[0]) == v:
            best[r["run_id"]] = (v, r)
    return best


def _median(vals):
    s = sorted(vals)
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


def seeded_tolerance(samples, floor, spread_factor):
    """max(floor, spread_factor x relative spread of the baseline) —
    r02-r05's 0.49% headline spread seeds a ~1% band under the default
    factor, and the floor keeps single-sample baselines honest."""
    if len(samples) >= 2:
        mean = sum(samples) / len(samples)
        if mean:
            spread = (max(samples) - min(samples)) / abs(mean)
            return max(floor, spread_factor * spread)
    return floor


def moving_bucket(base_rec, cand_rec):
    """(bucket, delta_ms, pct) of the largest-moving attribution
    bucket between two records, or None when either side has no
    attribution recorded."""
    ba = (base_rec.get("attribution") or {}).get("buckets_ms_per_step")
    bb = (cand_rec.get("attribution") or {}).get("buckets_ms_per_step")
    if not ba or not bb:
        return None
    worst = None
    for name in set(ba) | set(bb):
        a, b = float(ba.get(name, 0.0)), float(bb.get(name, 0.0))
        d = b - a
        if worst is None or abs(d) > abs(worst[1]):
            pct = (100.0 * d / a) if a else (100.0 if d else 0.0)
            worst = (name, d, pct)
    return worst


def gate(baseline, candidate, floor=0.02, spread_factor=2.0,
         tolerances=None, metrics=None):
    """Compare candidate records against baseline records.

    Returns (failures, results): ``results`` is one dict per compared
    metric; ``failures`` the regressed subset.  Metrics present on only
    one side are reported but never fail the gate (a new metric is not
    a regression; a vanished one is a schema problem for review)."""
    tolerances = tolerances or {}
    by_metric_base = {}
    for r in baseline:
        by_metric_base.setdefault(r["metric"], []).append(r)
    by_metric_cand = {}
    for r in candidate:
        by_metric_cand.setdefault(r["metric"], []).append(r)

    results, failures = [], []
    for metric in sorted(set(by_metric_base) & set(by_metric_cand)):
        if metrics and metric not in metrics:
            continue
        unit = by_metric_cand[metric][0].get("unit", "")
        hib = higher_is_better(metric, unit)
        base_best = best_per_run(by_metric_base[metric], hib)
        cand_best = best_per_run(by_metric_cand[metric], hib)
        base_samples = [v for v, _r in base_best.values()]
        ref = _median(base_samples)
        tol = tolerances.get(
            metric, seeded_tolerance(base_samples, floor, spread_factor))
        # candidate = the newest run on the candidate side
        cand_run = max(
            cand_best, key=lambda rid: cand_best[rid][1]["time"])
        cand_val, cand_rec = cand_best[cand_run]
        rel = (cand_val - ref) / abs(ref) if ref else 0.0
        regressed = (rel < -tol) if hib else (rel > tol)
        # attribution vs the newest baseline run's BEST-block record —
        # the same min-of-blocks reduction the value comparison used,
        # so a noisy non-best block (say, one with a compile hiccup)
        # cannot misdirect the named bucket
        base_run = max(
            base_best, key=lambda rid: base_best[rid][1]["time"])
        base_rec = base_best[base_run][1]
        bucket = moving_bucket(base_rec, cand_rec) if regressed else None
        res = {"metric": metric, "unit": unit,
               "direction": "higher" if hib else "lower",
               "baseline": ref, "baseline_runs": len(base_samples),
               "candidate": cand_val, "candidate_run": cand_run,
               "delta_pct": 100.0 * rel, "band_pct": 100.0 * tol,
               "regressed": regressed}
        if bucket is not None:
            res["moving_bucket"] = {"name": bucket[0],
                                    "delta_ms": round(bucket[1], 4),
                                    "delta_pct": round(bucket[2], 1)}
        results.append(res)
        if regressed:
            failures.append(res)
    return failures, results


def _parse_tolerances(items):
    out = {}
    for item in items or ():
        if "=" not in item:
            raise ValueError("--tolerance wants metric=relative, got %r"
                             % item)
        k, v = item.split("=", 1)
        out[k] = float(v)
    return out


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--ledger",
                   help="one ledger holding both sides: candidate = "
                        "newest run, baseline = every earlier run")
    p.add_argument("--baseline", nargs="+", metavar="PATH",
                   help="baseline ledgers/run files (.jsonl or legacy "
                        "BENCH_r*.json driver captures)")
    p.add_argument("--candidate", nargs="+", metavar="PATH",
                   help="candidate ledger/run file(s); the newest run "
                        "inside is the one gated")
    p.add_argument("--floor", type=float, default=0.02,
                   help="minimum relative tolerance band (default 0.02)")
    p.add_argument("--spread-factor", type=float, default=2.0,
                   help="band = max(floor, factor x baseline relative "
                        "spread) (default 2.0)")
    p.add_argument("--tolerance", action="append", metavar="METRIC=REL",
                   help="pin a metric's band explicitly (repeatable)")
    p.add_argument("--metrics",
                   help="comma list: gate only these metrics")
    p.add_argument("--json", action="store_true",
                   help="machine-readable result object on stdout")
    args = p.parse_args(argv)

    try:
        tolerances = _parse_tolerances(args.tolerance)
    except ValueError as e:
        print("perf_gate: %s" % e, file=sys.stderr)
        return 2
    if args.ledger:
        records = load_records([args.ledger])
        runs = group_runs(records)
        if len(runs) < 2:
            print("perf_gate: ledger %s holds %d run(s); need a "
                  "baseline and a candidate" % (args.ledger, len(runs)),
                  file=sys.stderr)
            return 2
        ids = list(runs)
        candidate = runs[ids[-1]]
        baseline = [r for rid in ids[:-1] for r in runs[rid]]
    elif args.baseline and args.candidate:
        baseline = load_records(args.baseline)
        candidate = load_records(args.candidate)
    else:
        print("perf_gate: pass --ledger, or --baseline ... "
              "--candidate ...", file=sys.stderr)
        return 2
    if not baseline or not candidate:
        print("perf_gate: no usable records (baseline=%d candidate=%d)"
              % (len(baseline), len(candidate)), file=sys.stderr)
        return 2

    metrics = set(args.metrics.split(",")) if args.metrics else None
    failures, results = gate(
        baseline, candidate, floor=args.floor,
        spread_factor=args.spread_factor, tolerances=tolerances,
        metrics=metrics)
    if not results:
        print("perf_gate: no metric measured on both sides",
              file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps({"ok": not failures, "compared": len(results),
                          "failures": failures, "results": results},
                         indent=1, sort_keys=True))
    else:
        for res in results:
            line = ("%s %s: %.6g vs baseline %.6g (%+.2f%%, band "
                    "±%.2f%%, %s-is-better, %d baseline run(s))"
                    % ("FAIL" if res["regressed"] else "PASS",
                       res["metric"], res["candidate"], res["baseline"],
                       res["delta_pct"], res["band_pct"],
                       res["direction"], res["baseline_runs"]))
            mb = res.get("moving_bucket")
            if mb:
                line += ("; largest-moving attribution bucket: %s "
                         "%+.3f ms/step (%+.1f%%)"
                         % (mb["name"], mb["delta_ms"], mb["delta_pct"]))
            elif res["regressed"]:
                line += "; no attribution recorded on both sides"
            print(line)
    if failures:
        print("perf_gate: %d metric(s) regressed beyond their noise "
              "band: %s" % (len(failures),
                            ", ".join(f["metric"] for f in failures)),
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
