"""im2rec — build RecordIO image datasets (reference parity:
tools/im2rec.py / im2rec.cc).

Two modes, same as the reference:
  --list: walk an image directory and write a .lst file
          (``index\\tlabel\\trelative/path``), labels from subdirectory
          order, optional train/val split.
  (default): pack a .lst + image root into ``prefix.rec`` +
          ``prefix.idx`` (indexed RecordIO), JPEG-encoding each image
          with optional resize/quality — the file format
          ImageRecordIter and the native decoder consume.

Uses PIL instead of OpenCV (offline TPU host image path).
"""
import argparse
import io
import os
import random
import struct
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from mxnet_tpu import recordio

_EXTS = {".jpg", ".jpeg", ".png", ".bmp"}


def make_list(args):
    root = args.root
    classes = sorted(d for d in os.listdir(root)
                     if os.path.isdir(os.path.join(root, d)))
    label_of = {c: i for i, c in enumerate(classes)}
    items = []
    if classes:
        for c in classes:
            for dirpath, _dirs, files in os.walk(os.path.join(root, c)):
                for f in sorted(files):
                    if os.path.splitext(f)[1].lower() in _EXTS:
                        rel = os.path.relpath(os.path.join(dirpath, f),
                                              root)
                        items.append((label_of[c], rel))
    else:  # flat directory: label 0
        for f in sorted(os.listdir(root)):
            if os.path.splitext(f)[1].lower() in _EXTS:
                items.append((0, f))
    if args.shuffle:
        random.Random(args.seed).shuffle(items)
    n_train = int(len(items) * args.train_ratio)
    splits = [("", items)] if args.train_ratio >= 1.0 else [
        ("_train", items[:n_train]), ("_val", items[n_train:])]
    for suffix, part in splits:
        path = args.prefix + suffix + ".lst"
        with open(path, "w") as f:
            for i, (lab, rel) in enumerate(part):
                f.write("%d\t%f\t%s\n" % (i, float(lab), rel))
        print("wrote %s (%d items, %d classes)"
              % (path, len(part), max(1, len(classes))))


def _encode(path, args):
    from PIL import Image

    img = Image.open(path).convert("RGB")
    if args.resize > 0:
        w, h = img.size
        scale = args.resize / min(w, h)
        if scale != 1.0:
            img = img.resize((max(1, int(w * scale)),
                              max(1, int(h * scale))),
                             Image.BILINEAR)
    buf = io.BytesIO()
    img.save(buf, format="JPEG", quality=args.quality)
    return buf.getvalue()


def make_rec(args):
    # the prefix must name the .lst (directly or by adding the
    # extension) — guessing further could resolve to a previous run's
    # .rec and truncate it before reading
    lst = args.prefix if args.prefix.endswith(".lst") \
        else args.prefix + ".lst"
    if not os.path.exists(lst):
        raise SystemExit("list file %r not found (generate with --list)"
                         % lst)
    out_prefix = lst[:-len(".lst")]
    writer = recordio.MXIndexedRecordIO(out_prefix + ".idx",
                                        out_prefix + ".rec", "w")
    n = 0
    with open(lst) as f:
        for line in f:
            parts = line.strip().split("\t")
            if len(parts) < 3:
                continue
            # idx \t label [\t label2 ...] \t path — multi-label rows
            # keep every label (recordio.IRHeader supports arrays)
            idx, rel = int(parts[0]), parts[-1]
            labels = [float(x) for x in parts[1:-1]]
            label = labels[0] if len(labels) == 1 else labels
            try:
                payload = _encode(os.path.join(args.root, rel), args)
            except Exception as e:
                print("skipping %s: %s" % (rel, e), file=sys.stderr)
                continue
            header = recordio.IRHeader(0, label, idx, 0)
            writer.write_idx(idx, recordio.pack(header, payload))
            n += 1
            if n % 1000 == 0:
                print("packed %d images" % n)
    writer.close()
    print("wrote %s.rec / %s.idx (%d records)" % (out_prefix, out_prefix,
                                                  n))


def main():
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("prefix", help="output prefix (or .lst path when "
                                  "packing)")
    p.add_argument("root", help="image root directory")
    p.add_argument("--list", action="store_true",
                   help="generate the .lst instead of packing a .rec")
    p.add_argument("--shuffle", type=int, default=1)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--train-ratio", type=float, default=1.0)
    p.add_argument("--resize", type=int, default=0,
                   help="resize shorter side to this many pixels")
    p.add_argument("--quality", type=int, default=95)
    args = p.parse_args()
    if args.list:
        make_list(args)
    else:
        make_rec(args)


if __name__ == "__main__":
    main()
