#!/usr/bin/env python
"""Distributed job launcher (counterpart of the reference's
tools/launch.py + dmlc-core tracker).

`--launcher local -n N` forks 1 parameter-server process + N worker
processes on this machine with the DMLC_* env contract the framework's
KVStoreDist / parallel.init_distributed read — the same pattern the
reference's CI uses for dist kvstore tests (SURVEY §4).
"""
from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def main():
    parser = argparse.ArgumentParser(description="Launch a distributed job")
    parser.add_argument("-n", "--num-workers", type=int, required=True)
    parser.add_argument("-s", "--num-servers", type=int, default=1,
                        help="(accepted for parity; the TCP PS uses 1)")
    parser.add_argument("--launcher", default="local",
                        choices=["local"],
                        help="multi-host launch is delegated to the cluster "
                             "scheduler (set DMLC_* env per host)")
    parser.add_argument("--sync-dst-dir", default=None)
    parser.add_argument("command", nargs=argparse.REMAINDER)
    args = parser.parse_args()

    port = _free_port()
    base_env = dict(os.environ)
    base_env.update({
        "DMLC_PS_ROOT_URI": "127.0.0.1",
        "DMLC_PS_ROOT_PORT": str(port),
        "DMLC_NUM_WORKER": str(args.num_workers),
        "DMLC_NUM_SERVER": "1",
    })

    procs = []
    # server role
    server_env = dict(base_env, DMLC_ROLE="server")
    procs.append(subprocess.Popen(
        [sys.executable, "-c",
         "from mxnet_tpu.kvstore_server import run_server; run_server()"],
        env=server_env))
    # workers
    for rank in range(args.num_workers):
        env = dict(base_env, DMLC_ROLE="worker", DMLC_RANK=str(rank),
                   DMLC_WORKER_RANK=str(rank))
        procs.append(subprocess.Popen(args.command, env=env))

    rc = 0
    for p in procs[1:]:
        rc |= p.wait()
    procs[0].terminate()
    sys.exit(rc)


if __name__ == "__main__":
    main()
