"""Quantize a model to a gated int8 serving artifact.

The production half of ``contrib/quantization.py`` (the int8 graph
rewrite has run on the shared rewrite engine since PR 1): drive the
rewrite from a RECORDED calibration batch, measure the top-k accuracy
delta against the fp32 model of record, and emit an artifact ONLY when
the gate passes — a quantization run that degrades accuracy refuses to
produce anything deployable (exit code 3).  The artifact (symbol json +
int8 params + digest-bearing ``meta.json`` commit point) serves through
``Predictor.from_symbol`` / ``AsyncPredictor`` and is registered in the
``tools/prewarm.py`` model-spec registry (``resnet50_serving_int8``) so
warm-pool replicas come up already quantized::

    # quantize the built-in symbolic ResNet-50 at serving shapes
    python tools/quantize_model.py --model resnet50 --out art/ \
        --calib recorded_batch.npy

    # or any saved checkpoint (model.save_checkpoint files)
    python tools/quantize_model.py --symbol m-symbol.json \
        --params m-0000.params --out art/ --calib batch.npy

    # validate / smoke-serve an artifact
    python tools/quantize_model.py --check art/
    python tools/quantize_model.py --serve-smoke art/

Exit codes: 0 = OK, 1 = malformed input/artifact, 3 = accuracy gate
refused (no artifact written).  ``--json`` emits one machine-parsable
summary line on stdout.
"""
import argparse
import json
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def log(msg):
    print("[quantize] %s" % msg, file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# built-in symbolic model registry
# ---------------------------------------------------------------------------


def build_mlp(classes=10, dim=16, hidden=64):
    """The small calibration-speed model (tests, walkthroughs)."""
    import mxnet_tpu as mx

    data = mx.sym.var("data")
    h = mx.sym.FullyConnected(data, num_hidden=hidden, name="fc1")
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.FullyConnected(h, num_hidden=hidden, name="fc2")
    h = mx.sym.Activation(h, act_type="relu")
    out = mx.sym.FullyConnected(h, num_hidden=classes, name="fc3")
    return out, (8, dim)


def build_resnet50(classes=1000):
    """Symbolic ResNet-50 v1 at the resnet50_serving shapes: the int8
    path of record.  BN folds into the convs at quantize time
    (``fold_batchnorm``), so the rewritten graph is conv->conv int8."""
    import mxnet_tpu as mx

    def conv(d, name, nf, kernel, stride=(1, 1), pad=(0, 0)):
        return mx.sym.Convolution(d, num_filter=nf, kernel=kernel,
                                  stride=stride, pad=pad, no_bias=True,
                                  name=name)

    def bn(d, name):
        return mx.sym.BatchNorm(d, fix_gamma=False, eps=2e-5, name=name)

    def relu(d):
        return mx.sym.Activation(d, act_type="relu")

    def bottleneck(d, name, nf, stride, dim_match):
        b = relu(bn(conv(d, name + "_conv1", nf // 4, (1, 1)),
                    name + "_bn1"))
        b = relu(bn(conv(b, name + "_conv2", nf // 4, (3, 3), stride,
                         (1, 1)), name + "_bn2"))
        b = bn(conv(b, name + "_conv3", nf, (1, 1)), name + "_bn3")
        sc = d if dim_match else bn(
            conv(d, name + "_sc", nf, (1, 1), stride), name + "_scbn")
        return relu(mx.sym.elemwise_add(b, sc))

    data = mx.sym.var("data")
    body = relu(bn(conv(data, "conv0", 64, (7, 7), (2, 2), (3, 3)),
                   "bn0"))
    body = mx.sym.Pooling(body, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                          pool_type="max")
    for stage, (units, nf) in enumerate(
            zip((3, 4, 6, 3), (256, 512, 1024, 2048))):
        for unit in range(units):
            stride = (1, 1) if stage == 0 or unit > 0 else (2, 2)
            body = bottleneck(body, "stage%d_unit%d" % (stage, unit),
                              nf, stride, dim_match=unit > 0)
    body = mx.sym.Pooling(body, global_pool=True, pool_type="avg",
                          kernel=(7, 7))
    body = mx.sym.Flatten(body)
    return mx.sym.FullyConnected(body, num_hidden=classes,
                                 name="fc1000"), (4, 3, 224, 224)


MODELS = {"mlp": build_mlp, "resnet50": build_resnet50}


def init_params(sym, data_shape, seed=0):
    """Deterministic Xavier-ish random params for a built-in model (the
    CLI's stand-in for a trained checkpoint; pass --symbol/--params for
    real weights)."""
    from mxnet_tpu import nd

    rng = np.random.RandomState(seed)
    arg_shapes, _, aux_shapes = sym.infer_shape(data=data_shape)
    args, auxs = {}, {}
    for name, shp in zip(sym.list_arguments(), arg_shapes):
        if name == "data":
            continue
        if name.endswith("_gamma"):
            v = np.ones(shp, np.float32)
        elif name.endswith(("_beta", "_bias")):
            v = np.zeros(shp, np.float32)
        else:
            fan_in = int(np.prod(shp[1:])) or 1
            v = (rng.randn(*shp) * np.sqrt(2.0 / fan_in)) \
                .astype(np.float32)
        args[name] = nd.array(v)
    for name, shp in zip(sym.list_auxiliary_states(), aux_shapes):
        v = np.ones(shp, np.float32) if name.endswith("_moving_var") \
            else np.zeros(shp, np.float32)
        auxs[name] = nd.array(v)
    return args, auxs


def _load_calib(args, data_shape):
    if args.calib:
        try:
            batch = np.load(args.calib)
        except (OSError, ValueError) as e:
            raise SystemExit("--calib %s: cannot load (%s)"
                             % (args.calib, e))
        log("calibration batch of record: %s %s from %s"
            % (batch.shape, batch.dtype, args.calib))
        return batch
    rng = np.random.RandomState(args.seed + 1)
    batch = rng.rand(*data_shape).astype(np.float32)
    log("no --calib given: synthetic seeded batch %s (record a real "
        "serving batch for production gates)" % (batch.shape,))
    return batch


def run_quantize(args):
    from mxnet_tpu.contrib import quantization as q

    if args.symbol:
        import mxnet_tpu as mx
        from mxnet_tpu import nd

        if not args.params:
            raise SystemExit("--symbol needs --params")
        if not args.calib:
            # a loaded checkpoint carries no data-shape hint to
            # synthesize a batch from — and a *recorded* batch is the
            # whole point of gating a real model
            raise SystemExit("--symbol mode needs --calib (a recorded "
                             "calibration batch .npy)")
        sym = mx.sym.load(args.symbol)
        blob = nd.load(args.params)
        arg_params = {k.split(":", 1)[1]: v for k, v in blob.items()
                      if k.startswith("arg:")}
        aux_params = {k.split(":", 1)[1]: v for k, v in blob.items()
                      if k.startswith("aux:")}
        data_shape = None
    else:
        builder = MODELS.get(args.model)
        if builder is None:
            raise SystemExit("unknown --model %r; registered: %s "
                             "(or use --symbol/--params)"
                             % (args.model, ", ".join(sorted(MODELS))))
        sym, data_shape = builder()
        arg_params, aux_params = init_params(sym, data_shape,
                                             seed=args.seed)
        log("built %s (%d args, %d aux)" % (args.model, len(arg_params),
                                            len(aux_params)))
    calib = _load_calib(args, data_shape)
    try:
        qsym, qargs, qaux, report = q.quantize_serving_artifact(
            sym, arg_params, aux_params, calib,
            data_name=args.data_name,
            excluded_sym_names=args.exclude or None,
            topk=args.topk, max_delta=args.max_delta, logger=log)
    except q.QuantizationGateError as e:
        log("REFUSED: %s" % e)
        if args.json:
            print(json.dumps({"status": "refused", "error": str(e)}))
        return 3
    q.save_artifact(args.out, qsym, qargs, qaux, report)
    log("artifact committed to %s (top-%d agreement %.4f, delta %.4f "
        "<= %.4f)" % (args.out, report["topk"], report["agreement"],
                      report["delta"], report["max_delta"]))
    if args.json:
        print(json.dumps(dict(report, status="emitted", out=args.out)))
    return 0


def run_check(args):
    from mxnet_tpu.contrib import quantization as q

    problems = q.check_artifact(args.check)
    if not problems:
        _s, _a, _x, meta = q.load_artifact(args.check)
        print("%s: OK (int8, %d quantized layers, top-%s delta %s <= %s)"
              % (args.check, meta.get("quantized_layers", 0),
                 meta.get("topk"), meta.get("delta"),
                 meta.get("max_delta")))
        return 0
    for p in problems:
        print("MALFORMED: %s" % p, file=sys.stderr)
    return 1


def run_serve_smoke(args):
    """Load the artifact and serve one batch end-to-end through
    Predictor.from_symbol — the path AsyncPredictor replicas take."""
    from mxnet_tpu.contrib import quantization as q
    from mxnet_tpu.serving import Predictor

    qsym, qargs, qaux, meta = q.load_artifact(args.serve_smoke)
    shape = tuple(meta.get("data_shape") or ())
    dtype = np.dtype(meta.get("data_dtype") or "float32")
    if not shape:
        raise SystemExit("%s: meta carries no data_shape" %
                         args.serve_smoke)
    pred = Predictor.from_symbol(
        qsym, qargs, qaux, data_name=meta.get("data_name", "data"),
        chain=args.chain, batch_shape=shape, batch_dtype=dtype,
        aot_policy_tag="int8")
    rng = np.random.RandomState(args.seed)
    batch = rng.rand(*shape).astype(dtype) \
        if np.issubdtype(dtype, np.floating) else \
        rng.randint(0, 255, shape).astype(dtype)
    out = list(pred.predict([batch]))[0]
    ok = bool(np.all(np.isfinite(np.asarray(out, np.float32))))
    log("served %d rows -> output %s %s (finite=%s)"
        % (shape[0], out.shape, out.dtype, ok))
    if args.json:
        print(json.dumps({"status": "served" if ok else "nonfinite",
                          "rows": int(shape[0]),
                          "out_shape": [int(d) for d in out.shape]}))
    return 0 if ok else 1


def main(argv=None):
    p = argparse.ArgumentParser(
        description="Quantize a model to a gated int8 serving artifact "
                    "(or --check / --serve-smoke an existing one)")
    p.add_argument("--model", default="mlp",
                   help="built-in symbolic model: %s"
                        % ", ".join(sorted(MODELS)))
    p.add_argument("--symbol", help="saved symbol json (with --params; "
                                    "overrides --model)")
    p.add_argument("--params", help="saved params blob "
                                    "(model.save_checkpoint layout)")
    p.add_argument("--out", help="artifact output directory")
    p.add_argument("--calib", help="recorded calibration batch (.npy); "
                                   "default: synthetic seeded batch")
    p.add_argument("--data-name", default="data")
    p.add_argument("--exclude", action="append",
                   help="layer name to keep fp32 (repeatable)")
    p.add_argument("--topk", type=int, default=None,
                   help="accuracy-gate top-k (default: "
                        "MXNET_QUANTIZE_TOPK)")
    p.add_argument("--max-delta", type=float, default=None,
                   help="max tolerated top-k delta (default: "
                        "MXNET_QUANTIZE_MAX_DELTA)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--chain", type=int, default=2,
                   help="--serve-smoke dispatch chain")
    p.add_argument("--check", metavar="DIR",
                   help="validate an artifact instead of quantizing")
    p.add_argument("--serve-smoke", metavar="DIR",
                   help="serve one batch from an artifact through "
                        "Predictor.from_symbol")
    p.add_argument("--json", action="store_true",
                   help="emit one JSON summary line on stdout")
    args = p.parse_args(argv)
    if args.check:
        return run_check(args)
    if args.serve_smoke:
        return run_serve_smoke(args)
    if not args.out:
        p.error("--out is required in quantize mode (or use --check / "
                "--serve-smoke)")
    return run_quantize(args)


if __name__ == "__main__":
    sys.exit(main())
