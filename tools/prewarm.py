"""Pre-warm the AOT executable store: compile and persist every
executable a workload needs BEFORE rollout, so a restarting trainer or
a freshly spawned serving replica starts at warm-cache speed.

Given a model spec (the registry below) — or the signature manifest the
trainer/Predictor append to on their first compile — this builds the
exact callables the runtime jits and runs their ``prewarm`` entry
points through the store (``mxnet_tpu.aot``)::

    python tools/prewarm.py --model bench_resnet50 [--store DIR]
    python tools/prewarm.py --manifest [--store DIR]
    python tools/prewarm.py --check [--store DIR] [--max-age-days 90]

``--check`` mirrors ``autotune.py --check``: it validates the store
(schema, payload digests, environment staleness, manifest) and exits
nonzero on a malformed store — CI-friendly.  ``--json`` emits one
machine-parsable summary line on stdout (``bench.py BENCH_PREWARM=1``
consumes it to report ``cold_start_seconds``).

Model specs are intentionally the *same builders the benchmarks use*
(``bench.build_trainer``), so the content-hash keys match what the real
process looks up.
"""
import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))  # quantize_model (int8 spec)

# jax 0.4.x XLA:CPU splits large modules across parallel-codegen object
# files and executable serialization only captures the entry module — a
# deserialized ResNet-50-sized executable then aborts with "Symbols not
# found" (the AOT layer degrades it to a recompile, loudly).  Forcing a
# single codegen unit makes the serialized artifact self-contained.
# Must be in the environment BEFORE XLA first compiles, hence here at
# CLI start and not inside mxnet_tpu.  Runtime performance of the
# compiled program is unchanged; only compile-time parallelism is.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_cpu_parallel_codegen_split_count" not in _flags:
    os.environ["XLA_FLAGS"] = \
        (_flags + " --xla_cpu_parallel_codegen_split_count=1").strip()


def log(msg):
    print("[prewarm] %s" % msg, file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# model-spec registry: name -> builder(store, batch) yielding info dicts
# ---------------------------------------------------------------------------

MODELS = {}


def model(name, doc):
    def deco(fn):
        fn.doc = doc
        MODELS[name] = fn
        return fn
    return deco


@model("tiny_mlp", "2-layer MLP trainer + predictor at toy shapes "
                   "(seconds; exercises every path — used by the tests)")
def _tiny_mlp(store, batch=None, dtype_policy=None):
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import nd, gluon, parallel
    from mxnet_tpu.serving import Predictor

    batch = int(batch or 4)
    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(gluon.nn.Dense(8, activation="relu"))
        net.add(gluon.nn.Dense(2))
    net.initialize(mx.init.Xavier())
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = parallel.ShardedTrainer(
        net, lambda o, l: loss_fn(o, l), mesh=None, optimizer="sgd",
        aot=store, aot_spec="tiny_mlp", dtype_policy=dtype_policy)
    x = nd.array(np.zeros((batch, 16), np.float32))
    y = nd.array(np.zeros((batch,), np.float32))
    yield trainer.prewarm([x], y)
    pred, _ = Predictor.from_block(net, np.zeros((batch, 16), np.float32),
                                   chain=2, aot=store,
                                   aot_spec="tiny_mlp",
                                   dtype_policy=dtype_policy)
    for info in pred.prewarm():
        yield info


@model("bench_resnet50", "the bench.py trainer-of-record (ResNet-50 "
                         "bf16/fp32 fused step; BENCH_BATCH honored)")
def _bench_resnet50(store, batch=None, dtype_policy=None):
    import bench

    trainer, x, y, _b, _on_tpu = bench.build_trainer(
        batch=int(batch) if batch else None, aot=store,
        aot_spec="bench_resnet50", dtype_policy=dtype_policy)
    yield trainer.prewarm([x], y)


@model("resnet18_serving", "ResNet-18 serving replica (Predictor "
                           "chain=2) — the CPU-measurable cold-start "
                           "probe for the serving tier")
def _resnet18_serving(store, batch=None, dtype_policy=None):
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.serving import Predictor

    batch = int(batch or 8)
    net = vision.resnet18_v1(classes=1000)
    net.initialize(mx.init.Xavier())
    x = np.zeros((batch, 3, 224, 224), np.float32)
    pred, _ = Predictor.from_block(net, x, chain=2, aot=store,
                                   aot_spec="resnet18_serving",
                                   dtype_policy=dtype_policy)
    for info in pred.prewarm():
        yield info


@model("resnet50_serving", "the serving tier of record (perf_notes "
                           "'Small-batch serving'): ResNet-50 bs32 "
                           "uint8 input, chain=8, device-side top-5")
def _resnet50_serving(store, batch=None, dtype_policy=None):
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.serving import Predictor, uint8_normalizer

    import jax

    def top5(logits):
        _v, i = jax.lax.top_k(logits, 5)
        return i

    batch = int(batch or 32)
    net = vision.resnet50_v1(classes=1000)
    net.initialize(mx.init.Xavier())
    x = np.zeros((batch, 3, 224, 224), np.uint8)
    on_tpu = any(d.platform != "cpu" for d in jax.devices())
    prep = uint8_normalizer() if on_tpu \
        else uint8_normalizer(dtype="float32")
    pred, _ = Predictor.from_block(
        net, x, chain=8, preprocess=prep,
        postprocess=top5, aot=store, aot_spec="resnet50_serving",
        dtype_policy=dtype_policy)
    for info in pred.prewarm():
        yield info


@model("resnet50_serving_int8", "int8 variant of resnet50_serving: "
                                "accuracy-gated quantize (BN fold + "
                                "int8 rewrite) then prewarm the "
                                "quantized executables — warm-pool "
                                "replicas come up already quantized")
def _resnet50_serving_int8(store, batch=None, dtype_policy=None):
    import numpy as np

    import quantize_model as qm
    from mxnet_tpu.contrib import quantization as q
    from mxnet_tpu.serving import Predictor

    art = os.path.join(store.path, "quantized", "resnet50_serving_int8")
    try:
        # one load serves both validation and serving (a ResNet-50
        # params blob is too big to deserialize twice on the cold path)
        qsym, qargs, qaux, meta = q.load_artifact(art)
    except Exception:
        # no committed artifact (or a damaged one): rebuild through the
        # gate.  A refused gate aborts the spec — a degraded int8
        # replica must never be prewarmed into the fleet.
        log("building gated int8 artifact at %s" % art)
        sym, data_shape = qm.build_resnet50()
        if batch:
            data_shape = (int(batch),) + tuple(data_shape[1:])
        arg_p, aux_p = qm.init_params(sym, data_shape)
        calib = np.random.RandomState(1).rand(*data_shape) \
            .astype(np.float32)
        qsym, qargs, qaux, report = q.quantize_serving_artifact(
            sym, arg_p, aux_p, calib, logger=log)
        q.save_artifact(art, qsym, qargs, qaux, report)
        meta = dict(report)
    pred = Predictor.from_symbol(
        qsym, qargs, qaux, data_name=meta.get("data_name", "data"),
        chain=8, batch_shape=tuple(meta["data_shape"]),
        batch_dtype=meta.get("data_dtype", "float32"), aot=store,
        aot_spec="resnet50_serving_int8", aot_policy_tag="int8")
    for info in pred.prewarm():
        yield info


@model("lm_decode", "transformer-LM generation tier: the ring engine's "
                    "decode step plus every prefill length bucket, AND "
                    "the paged engine's chunk family (prefill chunk, "
                    "decode, speculative verify) — one manifest row "
                    "per signature; warms everything a decode replica "
                    "needs at spawn")
def _lm_decode(store, batch=None, dtype_policy=None):
    import mxnet_tpu as mx
    from mxnet_tpu import generate

    ex_dir = os.path.join(REPO, "examples")
    if ex_dir not in sys.path:
        sys.path.insert(0, ex_dir)
    from transformer_lm import TransformerLM

    # the bench_decode.py CPU-smoke decode configuration (the chip
    # spec passes --batch to widen slots); cache_len kept modest so
    # the prewarm stays seconds-level
    slots = int(batch or 4)
    mx.random.seed(0)
    lm = TransformerLM(vocab_size=256, d_model=64, n_heads=4,
                       n_layers=2, max_len=64)
    lm.initialize(mx.init.Xavier())
    eng = generate.GenerationEngine(
        lm, slots=slots, cache_len=64, buckets=[16, 32, 64],
        aot=store, aot_spec="lm_decode", dtype_policy=dtype_policy,
        sampling=generate.SamplingConfig(greedy=True))
    for info in eng.prewarm():
        yield info
    # the paged replica's three chunk-family signatures: a (1, chunk)
    # prefill chunk, the (slots, 1) decode step, and the (slots, K+1)
    # speculative verify — same model, same spec name, so a manifest
    # replay rebuilds both engines from this one entry point
    paged = generate.PagedGenerationEngine(
        lm, slots=slots, cache_len=64, page_size=16, prefill_chunk=16,
        spec_k=2, aot=store, aot_spec="lm_decode",
        dtype_policy=dtype_policy,
        sampling=generate.SamplingConfig(greedy=True))
    for info in paged.prewarm():
        yield info


# ---------------------------------------------------------------------------
# modes
# ---------------------------------------------------------------------------


def _resolve_store(path):
    from mxnet_tpu import aot

    if path:
        return aot.AOTStore(path)
    return aot.default_store()


def _run_specs(store, specs, batch, dtype_policy=None):
    infos = []
    for name in specs:
        if name not in MODELS:
            raise SystemExit(
                "unknown model spec %r; registered: %s"
                % (name, ", ".join(sorted(MODELS))))
        log("building %s%s ..." % (name, " [dtype_policy=%s]"
                                   % dtype_policy if dtype_policy else ""))
        t0 = time.perf_counter()
        for info in MODELS[name](store, batch=batch,
                                 dtype_policy=dtype_policy):
            info = dict(info or {})
            info["spec"] = name
            infos.append(info)
            log("  %-28s %-9s %6.1fs%s"
                % (info.get("label", "?"), info.get("status", "?"),
                   info.get("seconds", 0.0),
                   "  (compile %.1fs)" % info["compile_seconds"]
                   if info.get("compile_seconds") else ""))
        log("%s done in %.1fs" % (name, time.perf_counter() - t0))
    return infos


def run_prewarm(args):
    store = _resolve_store(args.store)
    log("store: %s" % store.path)
    t0 = time.perf_counter()
    infos = _run_specs(store, args.model, args.batch,
                       args.dtype_policy)
    total = time.perf_counter() - t0
    compiled = [i for i in infos if i.get("status") == "compiled"]
    hits = [i for i in infos if i.get("status") == "hit"]
    fallbacks = [i for i in infos
                 if i.get("status") in ("fallback", "disabled")]
    # the cold cost this store now absorbs: measured compile seconds
    # for fresh entries, recorded compile seconds for ones already
    # present — so warm reruns still report what cold would have cost
    cold = sum(i.get("compile_seconds") or 0.0 for i in infos)
    log("%d executables: %d compiled, %d already warm, %d fallbacks "
        "(%.1fs total)" % (len(infos), len(compiled), len(hits),
                           len(fallbacks), total))
    if fallbacks:
        log("WARNING: %d executable(s) could not use the AOT store"
            % len(fallbacks))
    if args.json:
        print(json.dumps({
            "store": store.path,
            "entries": infos,
            "compiled": len(compiled),
            "hits": len(hits),
            "fallbacks": len(fallbacks),
            "cold_seconds": round(cold, 2),
            "total_seconds": round(total, 2),
        }))
    return 0 if not fallbacks else 2


def run_manifest(args):
    store = _resolve_store(args.store)
    entries, problems = store.manifest_entries()
    for msg in problems:
        print("MALFORMED: %s" % msg, file=sys.stderr)
    if not entries and not problems:
        log("manifest at %s is empty — run the workload once with "
            "MXNET_AOT=1 (or prewarm --model) to record signatures"
            % store.manifest_path())
    specs, unknown = [], []
    # rebuild each (spec, dtype_policy) pair the manifest recorded: the
    # policy tag is part of the AOT key, so replaying a bf16_mixed row
    # under f32 would compile the WRONG executable and leave the
    # promised one cold.  An explicit --dtype-policy overrides all rows
    # (operator intent); the int8 spec carries its policy in the graph.
    groups = []
    for e in entries:
        spec = e.get("spec")
        if spec and spec in MODELS:
            pol = args.dtype_policy or e.get("dtype_policy") or None
            if pol in ("f32", "int8"):
                pol = None
            if spec not in specs:
                specs.append(spec)
            if (spec, pol) not in groups:
                groups.append((spec, pol))
        else:
            unknown.append(e)
    for e in unknown:
        log("skip manifest entry %s (%s): spec %r is not in this "
            "CLI's registry — prewarm it from its own entry point"
            % (e.get("key", "?")[:12], e.get("label"), e.get("spec")))
    infos = []
    for spec, pol in groups:
        infos += _run_specs(store, [spec], args.batch, pol)
    if args.json:
        print(json.dumps({"store": store.path, "specs": specs,
                          "spec_policies": [[s, p or "f32"]
                                            for s, p in groups],
                          "skipped": len(unknown),
                          "entries": infos}))
    if problems:
        return 1
    return 0 if all(i.get("status") in ("compiled", "hit", "warm")
                    for i in infos) else 2


def _check_paged_row(e):
    """Shape-consistency problems for one ``generate:paged_chunk``
    manifest row (empty list = healthy).  The paged engine compiles a
    closed family of signatures — page-pool leaves are rank-5 with the
    page length at axis 3, and the token block is one of (1, chunk) /
    (slots, 1) / (slots, K+1) — so a row whose recorded shapes disagree
    with its own page_size/prefill_chunk/spec_k extras means the store
    was written by a mismatched build and would miss at load."""
    who = "manifest entry %s (%s)" % (e.get("key", "?")[:12],
                                      e.get("label"))
    page = e.get("page_size")
    chunk = e.get("prefill_chunk")
    spec_k = e.get("spec_k")
    if page is None or chunk is None or spec_k is None:
        return ["%s: paged row missing page_size/prefill_chunk/spec_k "
                "extras" % who]
    sig = e.get("signature") or []
    leaves = [(tuple(s[0]), s[1]) for s in sig
              if isinstance(s, (list, tuple)) and len(s) >= 2
              and isinstance(s[0], (list, tuple))]
    msgs = []
    pools = [s for s, _d in leaves if len(s) == 5]
    if len(pools) < 2:
        msgs.append("%s: no page-pool leaves (rank-5) in the recorded "
                    "signature" % who)
    else:
        for s in pools[:2]:
            if s[3] != page:
                msgs.append("%s: pool page axis %d != page_size %d"
                            % (who, s[3], page))
    # the model params are float leaves; the engine's only rank-2
    # int32 leaves are, in flatten order, page_table (slots, P) then
    # the token block (B, C)
    rank2 = [s for s, d in leaves if len(s) == 2 and d == "int32"]
    if len(rank2) < 2:
        msgs.append("%s: no token-block leaf in the recorded signature"
                    % who)
    else:
        width = rank2[1][1]
        allowed = {1, chunk} | ({spec_k + 1} if spec_k else set())
        if width not in allowed:
            msgs.append("%s: token block width %d is none of the "
                        "compiled family %s (chunk=%d spec_k=%d)"
                        % (who, width, sorted(allowed), chunk, spec_k))
    return msgs


def run_check(args):
    from mxnet_tpu import dtype_policy as _dtp

    store = _resolve_store(args.store)
    problems, stale = store.check(max_age_days=args.max_age_days)
    entries = store.entries()
    manifest, _ = store.manifest_entries()
    for e in manifest:
        if e.get("label") == "generate:paged_chunk":
            problems.extend(_check_paged_row(e))
    # every manifest signature must carry a recognized dtype-policy tag
    # (a registered policy name, or "int8" for quantized artifacts): a
    # wrong tag would prewarm the wrong executable.  Rows recorded
    # BEFORE the tag existed were f32 by construction (current builds
    # always stamp one) — reported as LEGACY, not fatal, so a store
    # that was green yesterday stays green.
    known_tags = set(_dtp.list_policies()) | {"int8"}
    legacy = []
    for e in manifest:
        tag = e.get("dtype_policy")
        if tag is None:
            legacy.append(
                "manifest entry %s (%s): no dtype_policy tag "
                "(pre-policy row, implied f32) — re-record with a "
                "current build to tag it"
                % (e.get("key", "?")[:12], e.get("label")))
        elif tag not in known_tags:
            problems.append(
                "manifest entry %s (%s): unknown dtype_policy %r "
                "(known: %s)" % (e.get("key", "?")[:12],
                                 e.get("label"), tag,
                                 sorted(known_tags)))
    print("%s: %d executables, %d manifest signatures"
          % (store.path, len(entries), len(manifest)))
    for key, meta in entries:
        print("  %s  %-28s %s  %.1fs compile"
              % (key[:12], meta.get("label", "?"),
                 (meta.get("fingerprint") or {}).get("backend", "?"),
                 meta.get("compile_seconds") or 0.0))
    for msg in stale:
        print("STALE: %s" % msg)
    for msg in legacy:
        print("LEGACY: %s" % msg)
    for msg in problems:
        print("MALFORMED: %s" % msg, file=sys.stderr)
    return 1 if problems else 0


def main(argv=None):
    p = argparse.ArgumentParser(
        description="Compile + persist a workload's executables into "
                    "the AOT store ahead of rollout (or --check the "
                    "store's integrity)")
    p.add_argument("--store", help="store directory (default: "
                                   "MXNET_AOT_DIR)")
    p.add_argument("--model", action="append",
                   help="model spec to prewarm (repeatable): %s"
                        % ", ".join(sorted(MODELS)))
    p.add_argument("--manifest", action="store_true",
                   help="prewarm every spec recorded in the store's "
                        "signature manifest")
    p.add_argument("--check", action="store_true",
                   help="validate the store instead of compiling; "
                        "nonzero exit on a malformed store")
    p.add_argument("--dtype-policy", default=None,
                   help="mixed-precision dtype policy for the built "
                        "specs (f32/bf16_mixed/bf16_pure; default: the "
                        "MXNET_DTYPE_POLICY env default) — each policy "
                        "compiles its own AOT entries, keyed apart by "
                        "the policy tag")
    p.add_argument("--batch", type=int,
                   help="override the spec's batch size")
    p.add_argument("--json", action="store_true",
                   help="emit one JSON summary line on stdout")
    p.add_argument("--max-age-days", type=float, default=90.0,
                   help="--check: flag entries older than this")
    args = p.parse_args(argv)
    if args.check:
        return run_check(args)
    if args.manifest:
        return run_manifest(args)
    if not args.model:
        p.error("pick a mode: --model NAME (see --help for the "
                "registry), --manifest, or --check")
    return run_prewarm(args)


if __name__ == "__main__":
    sys.exit(main())
