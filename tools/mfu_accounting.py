"""Roofline accounting for the bench.py train step (VERDICT r4 #1).

Builds the exact bench.py trainer (ResNet-50 v1, bf16 compute + fp32
master, momentum SGD, one fused XLA program), compiles it, pulls XLA's
own cost analysis (flops + bytes accessed) for the compiled program,
times real steps, and decomposes the step time against the ceilings
measured by tools/bench_mfu.py:

    t_compute        = flops / conv_ceiling   (MXU lower bound; real)
    t_memory_upper   = bytes / stream_bw      (pre-fusion byte count ->
                                               an UPPER bound on memory
                                               time, not a lower bound)
    implied_hbm_gbs  = bytes / measured_step  (the rate the pre-fusion
                                               traffic would require)

`cost_analysis` counts bytes before fusion, so t_memory_upper can
exceed the measured step; the decisive signals for "memory-bound" are
(a) t_compute << measured (the MXU is idle most of the step) and
(b) implied_hbm_gbs at or above the chip's stream bandwidth (even with
fusion discounting real traffic, the program is bandwidth-limited).

Run on an idle chip:
    python tools/mfu_accounting.py [--batch 256] [--json docs/mfu_accounting.json]
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

T0 = time.time()


def log(msg):
    print("[acct %6.1fs] %s" % (time.time() - T0, msg), file=sys.stderr,
          flush=True)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int,
                   default=int(os.environ.get("BENCH_BATCH", "256")))
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--json", default=None)
    p.add_argument("--mfu-probe",
                   default=os.path.join(os.path.dirname(os.path.dirname(
                       os.path.abspath(__file__))), "docs",
                       "mfu_probe.json"))
    args = p.parse_args()

    import jax

    from mxnet_tpu import random as _random
    import bench

    # the exact bench.py program (shared builder, same model/optimizer/
    # dtype/synthetic data) so the accounting describes the headline run
    trainer, x, y, batch, on_tpu = bench.build_trainer(args.batch)
    steps = args.steps if on_tpu else 2
    log("devices=%s batch=%d" % (jax.devices(), batch))

    loss = trainer.step([x], y)  # compile + init
    log("warmup done (loss=%.3f)" % float(loss))

    # XLA's own accounting of the compiled fused program
    lowered = trainer._step_fn.lower(
        trainer.param_arrays, trainer.opt_state, (x._data,), y._data,
        _random.next_key())
    cost = lowered.compile().cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0]
    flops = float(cost.get("flops", 0))
    bytes_acc = float(cost.get("bytes accessed", 0))
    log("cost_analysis: %.1f GFLOP, %.2f GB accessed per step"
        % (flops / 1e9, bytes_acc / 1e9))

    # time real steps (async dispatch; final loss fetch forces the chain)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = trainer.step([x], y)
    lv = float(loss)
    secs = (time.perf_counter() - t0) / steps
    img_s = batch / secs
    log("measured: %.1f ms/step, %.0f img/s (loss=%.3f)"
        % (secs * 1e3, img_s, lv))

    ceilings = {}
    if not os.path.exists(args.mfu_probe):
        log("WARNING: probe artifact %s not found — emitting raw "
            "counters WITHOUT the roofline verdict (run "
            "tools/bench_mfu.py first)" % args.mfu_probe)
    else:
        with open(args.mfu_probe) as f:
            probe = json.load(f)
        ceilings = {
            "matmul_tflops": max(r["tflops"] for r in probe["matmul"]),
            "conv_tflops": probe["conv"]["tflops"],
            "hbm_gbs": probe["hbm"]["gb_per_s"],
        }

    out = {"batch": batch, "steps": steps, "ms_per_step": secs * 1e3,
           "img_per_sec": img_s, "xla_gflop_per_step": flops / 1e9,
           "xla_gb_accessed_per_step": bytes_acc / 1e9,
           "arithmetic_intensity_flop_per_byte":
               flops / bytes_acc if bytes_acc else None,
           "ceilings": ceilings}
    if ceilings:
        t_compute = flops / (ceilings["conv_tflops"] * 1e12)
        t_memory_upper = bytes_acc / (ceilings["hbm_gbs"] * 1e9)
        implied_gbs = bytes_acc / secs / 1e9
        # memory-bound iff the MXU lower bound explains well under the
        # measured time AND the pre-fusion traffic would need >= the
        # chip's stream rate (see module docstring)
        memory_bound = t_compute < 0.7 * secs and \
            implied_gbs >= 0.8 * ceilings["hbm_gbs"]
        out.update({
            "t_compute_ms": t_compute * 1e3,
            "t_memory_upper_ms": t_memory_upper * 1e3,
            "implied_hbm_gbs_prefusion": implied_gbs,
            "mxu_busy_fraction": t_compute / secs,
            "roofline_bound": "memory" if memory_bound else "compute",
        })
    print(json.dumps(out, indent=1))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=1)
        log("wrote %s" % args.json)


if __name__ == "__main__":
    main()
