"""Pretty-print (and diff) mxnet_tpu telemetry JSON snapshots.

Reads the artifact written by ``mxnet_tpu.telemetry.dump(path)`` (or by
a running ``TelemetryReporter``'s ``path=``) and renders the top-N
series as a table: counters/gauges by value, histograms as
count/sum/mean/p50/p99.

    python tools/telemetry_dump.py snap.json [--top 20]
    python tools/telemetry_dump.py --diff before.json after.json

``--diff`` aligns series by (metric, labels) and prints deltas —
the before/after view for bench runs (counter/histogram deltas are the
work done between the snapshots; gauges show old -> new).
"""
import argparse
import json
import sys

_INF = float("inf")


def _load(path):
    try:
        with open(path) as f:
            data = json.load(f)
    except OSError as e:
        raise SystemExit("%s: cannot read (%s)" % (path, e))
    except ValueError as e:
        # truncated/garbage file (e.g. a dump interrupted before the
        # atomic-writer landed): a clear message + nonzero exit, not a
        # json traceback
        raise SystemExit("%s: malformed JSON (%s)" % (path, e))
    if not isinstance(data, dict) or "metrics" not in data:
        raise SystemExit("%s: not a telemetry dump (no 'metrics' key)"
                         % path)
    return data


def _series_key(name, labels):
    return name + "".join(
        "{%s=%s}" % kv for kv in sorted(labels.items()))


def _num(v):
    """Undo the dump's RFC-8259-safe encoding: non-finite values ship
    as strings ("NaN"/"Infinity"/"-Infinity"), which float() parses."""
    return float(v) if isinstance(v, str) else v


def _quantile(buckets, q):
    """Bucket-interpolated quantile from cumulative [(le, count)]."""
    if not buckets:
        return None
    total = buckets[-1][1]
    if total == 0:
        return None
    rank = q * total
    prev_ub, prev_c = 0.0, 0
    for ub, c in buckets:
        ub = float(_num(ub))
        if c >= rank:
            if ub == _INF:
                return prev_ub
            if c == prev_c:
                return ub
            return prev_ub + (ub - prev_ub) * (rank - prev_c) / (c - prev_c)
        prev_ub, prev_c = ub, c
    return prev_ub


def _flatten(data):
    """dump payload -> {series_key: ("scalar", value) | ("hist", s)}."""
    out = {}
    for name, m in sorted(data["metrics"].items()):
        for s in m["series"]:
            key = _series_key(name, s.get("labels", {}))
            if m["type"] == "histogram":
                out[key] = ("hist", s)
            else:
                out[key] = ("scalar", _num(s.get("value", 0.0)))
    return out


def _fmt_num(v):
    if v is None:
        return "-"
    if isinstance(v, float) and (v != v or v in (_INF, -_INF)):
        return str(v)
    if isinstance(v, float) and v != int(v):
        return "%.6g" % v
    return "%d" % int(v)


def _hist_cells(s):
    n = s.get("count", 0)
    tot = s.get("sum", 0.0)
    mean = tot / n if n else None
    return (n, tot, mean, _quantile(s.get("buckets", []), 0.5),
            _quantile(s.get("buckets", []), 0.99))


def cmd_show(paths, top):
    for path in paths:
        data = _load(path)
        print("== %s (t=%s) ==" % (path, data.get("time")))
        flat = _flatten(data)
        scalars = [(k, v) for k, (kind, v) in flat.items()
                   if kind == "scalar"]
        hists = [(k, s) for k, (kind, s) in flat.items() if kind == "hist"]
        scalars.sort(key=lambda kv: -abs(kv[1]))
        print("%-64s %14s" % ("series", "value"))
        for k, v in scalars[:top]:
            print("%-64s %14s" % (k, _fmt_num(v)))
        if hists:
            print()
            print("%-52s %8s %10s %10s %10s %10s" % (
                "histogram", "count", "sum", "mean", "p50", "p99"))
            hists.sort(key=lambda kv: -kv[1].get("count", 0))
            for k, s in hists[:top]:
                n, tot, mean, p50, p99 = _hist_cells(s)
                print("%-52s %8d %10s %10s %10s %10s" % (
                    k, n, "%.4g" % tot, _fmt_num(mean), _fmt_num(p50),
                    _fmt_num(p99)))
        print()


def cmd_diff(path_a, path_b, top):
    data_a, data_b = _load(path_a), _load(path_b)
    a, b = _flatten(data_a), _flatten(data_b)
    fams_a, fams_b = set(data_a["metrics"]), set(data_b["metrics"])
    rows = []
    for key in sorted(set(a) | set(b)):
        kind_a, va = a.get(key, (None, None))
        kind_b, vb = b.get(key, (None, None))
        kind = kind_b or kind_a
        # a metric family present in only one snapshot (registered by a
        # different code version, or renamed between runs): flag it as
        # new/gone instead of diffing against a silent zero.  A label
        # SERIES missing on one side within a shared family still diffs
        # from zero (a counter's first increment is real work done).
        family = key.split("{", 1)[0]
        if family not in fams_a or family not in fams_b:
            tag = "new" if family not in fams_a else "gone"
            s = vb if va is None else va
            val = "count %d sum %.4g" % (s.get("count", 0),
                                         s.get("sum", 0.0)) \
                if kind == "hist" else _fmt_num(s)
            rows.append((_INF, "%-56s %s (%s)" % (key, tag, val)))
            continue
        if kind == "hist":
            na = va.get("count", 0) if va else 0
            nb = vb.get("count", 0) if vb else 0
            sa = va.get("sum", 0.0) if va else 0.0
            sb = vb.get("sum", 0.0) if vb else 0.0
            dn, ds = nb - na, sb - sa
            if dn or ds:
                rows.append((abs(dn), "%-56s count %+d  sum %+.4g  "
                             "mean/new %s" % (key, dn, ds,
                                              _fmt_num(ds / dn)
                                              if dn else "-")))
        else:
            va = va or 0.0
            vb = vb or 0.0
            if va != vb:
                rows.append((abs(vb - va), "%-56s %s -> %s (%+.6g)"
                             % (key, _fmt_num(va), _fmt_num(vb), vb - va)))
    rows.sort(key=lambda r: -r[0])
    print("diff %s -> %s (%d changed series)" % (path_a, path_b, len(rows)))
    for _, line in rows[:top]:
        print(line)


def main(argv=None):
    p = argparse.ArgumentParser(
        description="Pretty-print/diff mxnet_tpu telemetry dumps")
    p.add_argument("paths", nargs="*", help="telemetry dump JSON file(s)")
    p.add_argument("--top", type=int, default=20,
                   help="series per section (default 20)")
    p.add_argument("--diff", nargs=2, metavar=("A", "B"),
                   help="diff two dumps instead of printing them")
    args = p.parse_args(argv)
    if args.diff:
        if args.paths:
            p.error("--diff takes exactly two files and no positionals")
        cmd_diff(args.diff[0], args.diff[1], args.top)
    elif args.paths:
        cmd_show(args.paths, args.top)
    else:
        p.error("give dump file(s) or --diff A B")
    return 0


if __name__ == "__main__":
    sys.exit(main())
