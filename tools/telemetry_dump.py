"""Pretty-print (and diff) mxnet_tpu telemetry snapshots.

Reads the JSON artifact written by ``mxnet_tpu.telemetry.dump(path)``
(or a ``TelemetryReporter``'s ``path=``) **or** a saved Prometheus/
OpenMetrics text exposition (``curl :9100/metrics > snap.txt``) — the
exposition parser understands the exemplar suffix the tracing-enabled
scrape emits (``... # {trace_id="..."} value ts``) instead of crashing
on it.  Renders the top-N series as a table: counters/gauges by value,
histograms as count/sum/mean/p50/p99.

    python tools/telemetry_dump.py snap.json [--top 20]
    python tools/telemetry_dump.py --diff before.json after.json
    python tools/telemetry_dump.py --diff before.txt after.txt  # scrapes
    python tools/telemetry_dump.py --merge r0.json r1.json [--out pod.json]

``--diff`` aligns series by (metric, labels) and prints deltas —
the before/after view for bench runs (counter/histogram deltas are the
work done between the snapshots; gauges show old -> new).

``--merge`` folds N per-rank dumps into one pod-level view with the
fleet collector's semantics (``fleet.merge_metrics``: counters sum
exactly, histograms add bucket-additively, gauges take the max), so a
merged histogram's percentiles are the pooled fleet percentiles at
bucket resolution.  ``--out`` writes the merged dump as JSON (itself
loadable by this tool and ``--diff``-able).
"""
import argparse
import json
import os
import re
import sys

_INF = float("inf")

_TYPE_RE = re.compile(r"^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*) (\w+)$")
_HELP_RE = re.compile(r"^# HELP ([a-zA-Z_:][a-zA-Z0-9_:]*) (.*)$")
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(\S+)"
    r"(?:\s+-?[0-9.eE+]+)?"   # optional 0.0.4 sample timestamp
    r"(?:\s+#\s+\{.*)?$")     # trailing "# {...} v ts" = exemplar
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _parse_value(tok):
    if tok == "+Inf":
        return _INF
    if tok == "-Inf":
        return -_INF
    if tok == "NaN":
        return float("nan")
    return float(tok)


def _parse_exposition(text):
    """Prometheus/OpenMetrics text -> the telemetry.dump() JSON shape.

    Exemplar suffixes (`` # {trace_id="..."} value ts``) are stripped:
    they annotate a bucket observation, they are not part of the
    sample value this tool aggregates."""
    types, helps = {}, {}
    hist_series = {}   # (family, labels_key) -> row dict
    scalar_series = {}  # name -> [(labels, value)]
    order = []
    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        m = _TYPE_RE.match(line)
        if m:
            types[m.group(1)] = m.group(2)
            order.append(m.group(1))
            continue
        m = _HELP_RE.match(line)
        if m:
            helps[m.group(1)] = m.group(2)
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ValueError("unparsable exposition line: %r" % line)
        name, labelstr, valtok = m.group(1), m.group(2) or "", m.group(3)
        labels = dict(_LABEL_RE.findall(labelstr))
        value = _parse_value(valtok)
        family = re.sub(r"_(bucket|sum|count)$", "", name)
        if types.get(family) == "histogram" and name != family:
            le = labels.pop("le", None)
            key = (family, tuple(sorted(labels.items())))
            row = hist_series.setdefault(
                key, {"labels": labels, "buckets": [], "sum": 0.0,
                      "count": 0})
            if name.endswith("_bucket") and le is not None:
                row["buckets"].append([_parse_value(le), value])
            elif name.endswith("_sum"):
                row["sum"] = value
            elif name.endswith("_count"):
                row["count"] = value
        else:
            scalar_series.setdefault(name, []).append((labels, value))
    metrics = {}
    for name in order:
        kind = types[name]
        series = []
        out_name = name
        if kind == "histogram":
            for (fam, _lk), row in sorted(hist_series.items()):
                if fam == name:
                    row["buckets"].sort(key=lambda b: b[0])
                    series.append(row)
        else:
            rows = scalar_series.get(name)
            if rows is None and kind == "counter":
                # OpenMetrics counter family: TYPE names the family
                # without _total, samples carry it — normalize back
                # to the suffixed (registry) name
                rows = scalar_series.get(name + "_total")
                if rows is not None:
                    out_name = name + "_total"
            for labels, value in rows or []:
                series.append({"labels": labels, "value": value})
        metrics[out_name] = {"type": kind, "help": helps.get(name, ""),
                             "series": series}
    if not metrics:
        raise ValueError("no # TYPE lines — not an exposition")
    return {"format_version": "exposition", "time": None,
            "metrics": metrics}


def _load(path):
    try:
        with open(path) as f:
            text = f.read()
    except OSError as e:
        raise SystemExit("%s: cannot read (%s)" % (path, e))
    try:
        data = json.loads(text)
    except ValueError as json_err:
        # not JSON: a saved /metrics scrape parses too (exemplar
        # suffixes included); anything else is a clear message +
        # nonzero exit, not a traceback
        if "# TYPE" in text:
            try:
                return _parse_exposition(text)
            except ValueError as e:
                raise SystemExit("%s: malformed exposition (%s)"
                                 % (path, e))
        raise SystemExit("%s: malformed JSON (%s)" % (path, json_err))
    if not isinstance(data, dict) or "metrics" not in data:
        raise SystemExit("%s: not a telemetry dump (no 'metrics' key)"
                         % path)
    return data


def _series_key(name, labels):
    return name + "".join(
        "{%s=%s}" % kv for kv in sorted(labels.items()))


def _num(v):
    """Undo the dump's RFC-8259-safe encoding: non-finite values ship
    as strings ("NaN"/"Infinity"/"-Infinity"), which float() parses."""
    return float(v) if isinstance(v, str) else v


def _quantile(buckets, q):
    """Bucket-interpolated quantile from cumulative [(le, count)]."""
    if not buckets:
        return None
    total = buckets[-1][1]
    if total == 0:
        return None
    rank = q * total
    prev_ub, prev_c = 0.0, 0
    for ub, c in buckets:
        ub = float(_num(ub))
        if c >= rank:
            if ub == _INF:
                return prev_ub
            if c == prev_c:
                return ub
            return prev_ub + (ub - prev_ub) * (rank - prev_c) / (c - prev_c)
        prev_ub, prev_c = ub, c
    return prev_ub


def _flatten(data):
    """dump payload -> {series_key: ("scalar", value) | ("hist", s)}."""
    out = {}
    for name, m in sorted(data["metrics"].items()):
        for s in m["series"]:
            key = _series_key(name, s.get("labels", {}))
            if m["type"] == "histogram":
                out[key] = ("hist", s)
            else:
                out[key] = ("scalar", _num(s.get("value", 0.0)))
    return out


def _fmt_num(v):
    if v is None:
        return "-"
    if isinstance(v, float) and (v != v or v in (_INF, -_INF)):
        return str(v)
    if isinstance(v, float) and v != int(v):
        return "%.6g" % v
    return "%d" % int(v)


def _hist_cells(s):
    n = s.get("count", 0)
    tot = s.get("sum", 0.0)
    mean = tot / n if n else None
    return (n, tot, mean, _quantile(s.get("buckets", []), 0.5),
            _quantile(s.get("buckets", []), 0.99))


def cmd_show(paths, top):
    for path in paths:
        data = _load(path)
        print("== %s (t=%s) ==" % (path, data.get("time")))
        flat = _flatten(data)
        scalars = [(k, v) for k, (kind, v) in flat.items()
                   if kind == "scalar"]
        hists = [(k, s) for k, (kind, s) in flat.items() if kind == "hist"]
        scalars.sort(key=lambda kv: -abs(kv[1]))
        print("%-64s %14s" % ("series", "value"))
        for k, v in scalars[:top]:
            print("%-64s %14s" % (k, _fmt_num(v)))
        if hists:
            print()
            print("%-52s %8s %10s %10s %10s %10s" % (
                "histogram", "count", "sum", "mean", "p50", "p99"))
            hists.sort(key=lambda kv: -kv[1].get("count", 0))
            for k, s in hists[:top]:
                n, tot, mean, p50, p99 = _hist_cells(s)
                print("%-52s %8d %10s %10s %10s %10s" % (
                    k, n, "%.4g" % tot, _fmt_num(mean), _fmt_num(p50),
                    _fmt_num(p99)))
        print()


def cmd_merge(paths, top, out=None):
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from fleetz import load_fleet

    fleet = load_fleet()
    dumps = [_load(p) for p in paths]
    merged = {
        "format_version": 1,
        "time": max((d.get("time") or 0) for d in dumps) or None,
        "merged_from": list(paths),
        "metrics": fleet.merge_metrics([d["metrics"] for d in dumps]),
    }
    if out:
        with open(out, "w") as f:
            json.dump(merged, f, indent=2, sort_keys=True)
        print("wrote %s (%d inputs, %d metric families)"
              % (out, len(paths), len(merged["metrics"])))
    print("== merged %d dump(s) ==" % len(paths))
    flat = _flatten(merged)
    scalars = [(k, v) for k, (kind, v) in flat.items() if kind == "scalar"]
    hists = [(k, s) for k, (kind, s) in flat.items() if kind == "hist"]
    scalars.sort(key=lambda kv: -abs(kv[1]))
    print("%-64s %14s" % ("series", "value"))
    for k, v in scalars[:top]:
        print("%-64s %14s" % (k, _fmt_num(v)))
    if hists:
        print()
        print("%-52s %8s %10s %10s %10s %10s" % (
            "histogram", "count", "sum", "mean", "p50", "p99"))
        hists.sort(key=lambda kv: -kv[1].get("count", 0))
        for k, s in hists[:top]:
            n, tot, mean, p50, p99 = _hist_cells(s)
            print("%-52s %8d %10s %10s %10s %10s" % (
                k, n, "%.4g" % _num(tot), _fmt_num(mean), _fmt_num(p50),
                _fmt_num(p99)))


def cmd_diff(path_a, path_b, top):
    data_a, data_b = _load(path_a), _load(path_b)
    a, b = _flatten(data_a), _flatten(data_b)
    fams_a, fams_b = set(data_a["metrics"]), set(data_b["metrics"])
    rows = []
    for key in sorted(set(a) | set(b)):
        kind_a, va = a.get(key, (None, None))
        kind_b, vb = b.get(key, (None, None))
        kind = kind_b or kind_a
        # a metric family present in only one snapshot (registered by a
        # different code version, or renamed between runs): flag it as
        # new/gone instead of diffing against a silent zero.  A label
        # SERIES missing on one side within a shared family still diffs
        # from zero (a counter's first increment is real work done).
        family = key.split("{", 1)[0]
        if family not in fams_a or family not in fams_b:
            tag = "new" if family not in fams_a else "gone"
            s = vb if va is None else va
            val = "count %d sum %.4g" % (s.get("count", 0),
                                         s.get("sum", 0.0)) \
                if kind == "hist" else _fmt_num(s)
            rows.append((_INF, "%-56s %s (%s)" % (key, tag, val)))
            continue
        if kind == "hist":
            na = va.get("count", 0) if va else 0
            nb = vb.get("count", 0) if vb else 0
            sa = va.get("sum", 0.0) if va else 0.0
            sb = vb.get("sum", 0.0) if vb else 0.0
            dn, ds = nb - na, sb - sa
            if dn or ds:
                rows.append((abs(dn), "%-56s count %+d  sum %+.4g  "
                             "mean/new %s" % (key, dn, ds,
                                              _fmt_num(ds / dn)
                                              if dn else "-")))
        else:
            va = va or 0.0
            vb = vb or 0.0
            if va != vb:
                rows.append((abs(vb - va), "%-56s %s -> %s (%+.6g)"
                             % (key, _fmt_num(va), _fmt_num(vb), vb - va)))
    rows.sort(key=lambda r: -r[0])
    print("diff %s -> %s (%d changed series)" % (path_a, path_b, len(rows)))
    for _, line in rows[:top]:
        print(line)


def main(argv=None):
    p = argparse.ArgumentParser(
        description="Pretty-print/diff mxnet_tpu telemetry dumps")
    p.add_argument("paths", nargs="*", help="telemetry dump JSON file(s)")
    p.add_argument("--top", type=int, default=20,
                   help="series per section (default 20)")
    p.add_argument("--diff", nargs=2, metavar=("A", "B"),
                   help="diff two dumps instead of printing them")
    p.add_argument("--merge", nargs="+", metavar="DUMP",
                   help="merge N per-rank dumps (fleet semantics: "
                        "counters sum, histograms add bucket-additively)")
    p.add_argument("--out", help="with --merge: write the merged dump "
                                 "here as JSON")
    args = p.parse_args(argv)
    if args.diff and args.merge:
        p.error("--diff and --merge are mutually exclusive")
    if args.diff:
        if args.paths:
            p.error("--diff takes exactly two files and no positionals")
        cmd_diff(args.diff[0], args.diff[1], args.top)
    elif args.merge:
        if args.paths:
            p.error("--merge takes its files after the flag, "
                    "no positionals")
        cmd_merge(args.merge, args.top, out=args.out)
    elif args.paths:
        cmd_show(args.paths, args.top)
    else:
        p.error("give dump file(s), --diff A B, or --merge A B ...")
    return 0


if __name__ == "__main__":
    sys.exit(main())
